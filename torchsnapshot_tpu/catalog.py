"""Per-bucket snapshot catalog: the lifecycle layer for continuous checkpointing.

TorchSnapshot's design (PAPER.md) stops at single, independent snapshots.
The production workload this module serves is *continuous* multi-tenant
checkpointing: many jobs snapshotting every few steps into one bucket,
indefinitely. Three questions then need durable answers that no single
snapshot can carry — which snapshots exist, how they chain, and which can
be safely collected:

- **Catalog** — an append-only, atomically-updated record set under
  ``<bucket>/.catalog/``: one small JSON record per committed snapshot
  (job/tenant id, step, wall time, base pointer, chain length, byte
  attribution full-vs-dedup'd), written by rank 0 at commit time — after
  ``.snapshot_metadata`` lands, before the commit barrier releases — so a
  record's existence implies a committed snapshot. Each record is one
  atomic object write; concurrent jobs append without any read-modify-write
  race. The catalog is *advisory and reconstructable*: :meth:`Catalog.rebuild`
  re-derives records by scanning the bucket, and every consumer degrades
  gracefully when records are missing (a lost record just drops that
  snapshot out of its chain — snapshots are physically self-contained, see
  below).

- **Managed delta chains** — ``Snapshot.take(..., job=...)`` auto-selects
  the best ``base=``: the latest committed same-job snapshot from the
  catalog, unless its recorded chain is already ``max_chain_len`` deltas
  deep, in which case the take *rebases to a full snapshot*. Selection runs
  on rank 0 inside the existing preflight round (the resolved base rides
  the preflight broadcast, so every rank agrees by construction), and a
  per-process chain cache makes the steady-state lookup free of storage
  I/O.

- **Retention** — policies (keep-last-K, keep-hourly/daily, pins) computed
  per job over the catalog, whose retained set drives
  :meth:`Snapshot.gc`'s explicit keep-set parameter. The chain-aware
  guarantee: collecting ANY condemned prefix never breaks a retained
  snapshot's bit-exact restore. This holds structurally, not by careful
  bookkeeping: incremental dedup materializes shared objects under every
  snapshot root (fs hard links share inodes; cloud backends server-side
  copy), so each committed snapshot is physically self-contained and a
  delta never *reads through* its base at restore time. The catalog's
  chain-safety validator (:func:`validate_chain_closure`) re-checks that
  invariant against the retained manifests before any deletion, so a
  future layout that DID share bytes across roots would fail loudly
  instead of tearing a live chain.

Chain-aware restore needs no new machinery: the content-addressed read
cache (``storage_plugins/cache.py``) keys data objects by their sidecar
digests, which dedup'd chain objects share — a warm replica following a
chain reads only each delta's new bytes from origin (proven in
``benchmarks/continuous/``).

Crash convergence of retention GC (chaos-tested in ``tests/test_chaos.py``):
condemned snapshots are deleted in a fixed order — ``.snapshot_metadata``
first (the snapshot atomically stops being restorable-from), then the data
tree, then the catalog record last. A crash at any point leaves either a
committed snapshot (nothing deleted yet) or an uncommitted tree whose
still-present record marks it as a half-collected *zombie* that the next
GC run finishes off; records are only removed once their tree is gone.
Re-running GC therefore always converges to exactly the retained set.

See ``docs/lifecycle.md`` for the record schema, the retention-policy
grammar, and the operational guarantees.
"""

from __future__ import annotations

import asyncio
import fnmatch
import hashlib
import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import telemetry
from .io_types import ReadIO, StoragePlugin, WriteIO
from .manifest import SNAPSHOT_METADATA_FNAME
from .storage_plugin import url_to_storage_plugin_in_event_loop
from .utils import knobs

logger = logging.getLogger(__name__)

# Everything catalog-owned lives under this prefix of the bucket. Records
# are append-only (one atomic object per committed snapshot); pins are
# marker objects an operator adds/removes explicitly.
CATALOG_DIR = ".catalog"
RECORD_DIR = f"{CATALOG_DIR}/records"
PIN_DIR = f"{CATALOG_DIR}/pins"
# Per-step telemetry rollups (telemetry/steprecord.py) ride beside the
# catalog records: same per-job grouping, same name/step object identity,
# same lifecycle (retention GC keeps a step record exactly as long as its
# snapshot's catalog record).
STEP_TELEMETRY_DIR = f"{CATALOG_DIR}/telemetry"
ROLLOUT_TELEMETRY_DIR = f"{CATALOG_DIR}/rollouts"

# Bump when the record layout changes incompatibly. Loaders skip records
# with a NEWER schema (a downgraded reader must not misinterpret them) and
# accept older ones forever.
CATALOG_SCHEMA_VERSION = 1

# Sentinel scheme carried in the ``base=`` slot through take planning:
# "resolve the base from the catalog on rank 0 during preflight". Never a
# real storage URL.
_AUTO_BASE_SCHEME = "catalog-auto://"

# Per-process chain cache: (bucket_url, job) -> (snapshot name, chain_len)
# of the most recently committed same-job snapshot this process took or
# looked up. Makes steady-state auto-base selection free of storage I/O;
# retention GC invalidates the bucket's entries (a cached base may have
# been condemned). A stale entry is safe regardless: the base fallback
# ladder in snapshot.py degrades a vanished/unreadable base to a full
# snapshot.
_CHAIN_CACHE: Dict[Tuple[str, str], Tuple[str, int]] = {}


# ---------------------------------------------------------------------------
# Bucket/path plumbing
# ---------------------------------------------------------------------------

def split_bucket(path: str) -> Optional[Tuple[str, str]]:
    """Split a snapshot path/URL into ``(bucket_url, snapshot_name)``.

    The bucket is the snapshot's parent prefix — where the catalog lives
    and what retention GC scans. Returns None when the path has no parent
    (a snapshot taken at a filesystem/bucket root has no bucket to catalog
    into; such takes simply go unrecorded)."""
    if "://" in path:
        proto, _, rest = path.partition("://")
        rest = rest.rstrip("/")
        if "/" not in rest or not rest:
            return None
        parent, _, name = rest.rpartition("/")
        if not parent or not name:
            return None
        return f"{proto}://{parent}", name
    p = os.path.abspath(path).rstrip("/")
    parent, name = os.path.split(p)
    if not name or parent in ("", "/", p):
        return None
    return parent, name


def join_bucket(bucket_url: str, name: str) -> str:
    """Inverse of :func:`split_bucket`."""
    return f"{bucket_url.rstrip('/')}/{name}"


def _slug(text: str) -> str:
    """Filesystem/object-safe token for ``text``, collision-disambiguated:
    keeps [A-Za-z0-9_-] verbatim and appends a short content hash whenever
    anything was altered (two jobs must never share a record directory)."""
    safe = re.sub(r"[^A-Za-z0-9_\-]", "_", text) or "_"
    if safe != text:
        safe = f"{safe}-{hashlib.sha1(text.encode()).hexdigest()[:8]}"
    return safe


def _name_key(name: str) -> str:
    """Stable per-snapshot-name token used in record/pin object names: the
    same snapshot path always maps to the same object, so re-taking a name
    overwrites its record atomically instead of accumulating duplicates."""
    return hashlib.sha1(name.encode()).hexdigest()[:12]


def record_path(job: str, name: str, step: int) -> str:
    """Catalog object path (bucket-relative) of one snapshot's record.
    Grouped per job so same-job listing is one prefix scan; the step is
    zero-padded so lexical order is chain order for the common
    monotonic-step case (selection itself sorts numerically)."""
    return (
        f"{RECORD_DIR}/{_slug(job)}/"
        f"{max(0, int(step)):020d}-{_name_key(name)}.json"
    )


def pin_path(name: str) -> str:
    return f"{PIN_DIR}/{_name_key(name)}.json"


def step_record_path(job: str, name: str, step: int) -> str:
    """Catalog object path of one snapshot's step-telemetry record —
    :func:`record_path`'s layout under :data:`STEP_TELEMETRY_DIR`, so a
    re-taken name overwrites its record and same-job listing is one prefix
    scan."""
    return (
        f"{STEP_TELEMETRY_DIR}/{_slug(job)}/"
        f"{max(0, int(step)):020d}-{_name_key(name)}.json"
    )


def rollout_record_path(job: str, name: str, step: Optional[int], rank: int) -> str:
    """Catalog object path of one RANK's rollout (restore-side) record.
    Same layout as :func:`step_record_path` under a ``rollouts/`` sibling,
    with the rank in the filename: restores append per-process (there is no
    commit barrier to elect a merger behind), so per-rank files avoid
    last-writer-wins collisions by construction."""
    return (
        f"{ROLLOUT_TELEMETRY_DIR}/{_slug(job)}/"
        f"{max(0, int(step or 0)):020d}-{_name_key(name)}_r{int(rank)}.json"
    )


def _run(coro, loop: Optional[asyncio.AbstractEventLoop]):
    if loop is not None:
        return loop.run_until_complete(coro)
    inner = asyncio.new_event_loop()
    try:
        return inner.run_until_complete(coro)
    finally:
        inner.close()


# ---------------------------------------------------------------------------
# Records
# ---------------------------------------------------------------------------

@dataclass
class CatalogRecord:
    """One committed snapshot, as the catalog knows it.

    ``bytes_total`` is the snapshot's full logical payload (every storage
    object's size, from its own checksum sidecars); ``bytes_deduped`` is
    the share of that payload whose content identity (v1 whole-object
    sha256 or v2 tree-digest root) already existed in the base snapshot's
    sidecars — i.e. bytes the incremental machinery could share instead of
    rewriting; ``bytes_written`` is the remainder (the delta's new bytes).
    Derived from sidecar digests, so the attribution needs no collectives
    and is exact up to link-in failures (a failed hard link falls back to
    a full write but still counts as dedup-shareable here). All three are
    0 when sidecars were unavailable (checksums off)."""

    name: str
    job: str
    step: int
    wall_time: float
    base: Optional[str] = None  # base snapshot NAME (same bucket) or path
    chain_len: int = 0  # 0 = full snapshot; k = k-th delta of its chain
    world_size: int = 1
    bytes_total: int = 0
    bytes_written: int = 0
    bytes_deduped: int = 0
    schema: int = CATALOG_SCHEMA_VERSION
    # Bucket-relative catalog object this record was loaded from (absent on
    # freshly-built records until append assigns it). Not serialized.
    path: Optional[str] = field(default=None, compare=False)

    def to_json(self) -> str:
        return json.dumps(
            {
                "schema": self.schema,
                "name": self.name,
                "job": self.job,
                "step": self.step,
                "wall_time": self.wall_time,
                "base": self.base,
                "chain_len": self.chain_len,
                "world_size": self.world_size,
                "bytes_total": self.bytes_total,
                "bytes_written": self.bytes_written,
                "bytes_deduped": self.bytes_deduped,
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, s: str) -> "CatalogRecord":
        d = json.loads(s)
        if not isinstance(d, dict):
            raise ValueError("catalog record is not a JSON object")
        return cls(
            name=str(d["name"]),
            job=str(d.get("job", "")),
            step=int(d.get("step", -1)),
            wall_time=float(d.get("wall_time", 0.0)),
            base=d.get("base"),
            chain_len=int(d.get("chain_len", 0)),
            world_size=int(d.get("world_size", 1)),
            bytes_total=int(d.get("bytes_total", 0)),
            bytes_written=int(d.get("bytes_written", 0)),
            bytes_deduped=int(d.get("bytes_deduped", 0)),
            schema=int(d.get("schema", 1)),
        )

    @property
    def order_key(self) -> Tuple[int, float, str]:
        """Recency order within one job: step first (the training clock),
        wall time as the tiebreak, name last for determinism."""
        return (self.step, self.wall_time, self.name)


class Catalog:
    """Handle on one bucket's catalog. Opens the bucket through the same
    ``url_to_storage_plugin`` stack snapshots use (read cache and fault
    injection wrap it identically), on a caller-owned or private event
    loop. Cheap to construct; close() releases the plugin."""

    def __init__(
        self,
        bucket_url: str,
        event_loop: Optional[asyncio.AbstractEventLoop] = None,
        storage: Optional[StoragePlugin] = None,
    ) -> None:
        self.bucket_url = bucket_url
        self._own_loop = event_loop is None
        self._loop = event_loop or asyncio.new_event_loop()
        self._own_storage = storage is None
        self._storage = storage or url_to_storage_plugin_in_event_loop(
            bucket_url, self._loop
        )

    def close(self) -> None:
        if self._own_storage:
            self._storage.sync_close(self._loop)
        if self._own_loop:
            self._loop.close()

    def __enter__(self) -> "Catalog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- append
    def append(self, record: CatalogRecord) -> bool:
        """Atomically write one record (one object; plugin writes are
        atomic). Returns False on any failure — the catalog is fail-open:
        a missed append degrades the chain/retention view, never the
        snapshot commit it rides alongside."""
        path = record_path(record.job, record.name, record.step)
        try:
            with telemetry.span(
                "catalog.append", cat="catalog", path=path
            ):
                self._storage.sync_write(
                    WriteIO(path=path, buf=record.to_json().encode()),
                    self._loop,
                )
            record.path = path
            telemetry.counter_add("catalog.appends")
            return True
        except Exception:  # noqa: BLE001 - fail-open by contract
            telemetry.counter_add("catalog.append_failures")
            logger.warning(
                "catalog append for %s under %s failed (snapshot commit "
                "unaffected; `catalog rebuild` can reconstruct the record)",
                record.name,
                self.bucket_url,
                exc_info=True,
            )
            return False

    def append_step_telemetry(self, record: Dict[str, Any]) -> bool:
        """Atomically write one step-telemetry record (built by
        ``telemetry.steprecord.build_step_record``) beside the snapshot's
        catalog record. Fail-open like :meth:`append` — a missed record
        loses one point of the trend line, never the commit, and the point
        is rebuildable from the snapshot's per-rank artifacts."""
        path = step_record_path(
            str(record.get("job", "")),
            str(record.get("name", "")),
            int(record.get("step", 0)),
        )
        try:
            from .telemetry import steprecord

            with telemetry.span(
                "catalog.step_append", cat="catalog", path=path
            ):
                self._storage.sync_write(
                    WriteIO(
                        path=path, buf=steprecord.dumps_step_record(record)
                    ),
                    self._loop,
                )
            telemetry.counter_add("catalog.step_appends")
            return True
        except Exception:  # noqa: BLE001 - fail-open by contract
            telemetry.counter_add("catalog.step_append_failures")
            logger.warning(
                "step-telemetry append for %s under %s failed (snapshot "
                "commit unaffected; the record is rebuildable from the "
                "snapshot's .telemetry artifacts)",
                record.get("name"),
                self.bucket_url,
                exc_info=True,
            )
            return False

    def load_step_telemetry(
        self, job: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """All readable step-telemetry records, step order (per job),
        de-duplicated by snapshot name — the step series the health
        detectors and the ``timeline`` CLI run over. Unreadable or
        newer-schema records are skipped with one warning each."""
        from .telemetry import steprecord

        prefix = (
            STEP_TELEMETRY_DIR
            if job is None
            else f"{STEP_TELEMETRY_DIR}/{_slug(job)}"
        )
        with telemetry.span("catalog.step_scan", cat="catalog", path=prefix):
            try:
                paths = _run(self._storage.list_prefix(prefix), self._loop)
            except FileNotFoundError:
                return []
            by_name: Dict[str, Dict[str, Any]] = {}
            for p in sorted(paths):
                if not p.endswith(".json"):
                    continue
                try:
                    read_io = ReadIO(path=p)
                    self._storage.sync_read(read_io, self._loop)
                    rec = steprecord.parse_step_record(
                        read_io.buf.getvalue()
                    )
                except Exception:  # noqa: BLE001 - degrade, never fail
                    logger.warning(
                        "unreadable step-telemetry record %s under %s "
                        "(skipped)",
                        p,
                        self.bucket_url,
                        exc_info=True,
                    )
                    continue
                if job is not None and rec.get("job") != job:
                    continue
                key = str(rec.get("name", p))
                prev = by_name.get(key)
                if prev is None or (
                    rec.get("step", 0),
                    rec.get("created_unix", 0.0),
                ) >= (prev.get("step", 0), prev.get("created_unix", 0.0)):
                    by_name[key] = rec
        return sorted(
            by_name.values(),
            key=lambda r: (r.get("step", 0), r.get("created_unix", 0.0)),
        )

    def append_rollout_record(self, record: Dict[str, Any]) -> bool:
        """Atomically write one rank's rollout (restore-side) record —
        built by ``telemetry.steprecord.build_rollout_record``. Fail-open
        like :meth:`append_step_telemetry`: a missed record loses one point
        of the restore trend line, never the restore itself."""
        path = rollout_record_path(
            str(record.get("job", "")),
            str(record.get("name", "")),
            record.get("step"),
            int(record.get("rank", 0) or 0),
        )
        try:
            from .telemetry import steprecord

            with telemetry.span(
                "catalog.rollout_append", cat="catalog", path=path
            ):
                self._storage.sync_write(
                    WriteIO(
                        path=path, buf=steprecord.dumps_rollout_record(record)
                    ),
                    self._loop,
                )
            telemetry.counter_add("catalog.rollout_appends")
            return True
        except Exception:  # noqa: BLE001 - fail-open by contract
            telemetry.counter_add("catalog.rollout_append_failures")
            logger.warning(
                "rollout record append for %s under %s failed (restore "
                "unaffected)",
                record.get("name"),
                self.bucket_url,
                exc_info=True,
            )
            return False

    def load_rollout_telemetry(
        self, job: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """All readable rollout records, (step, rank, created) order.
        Per-rank records are NOT merged — restore skew across ranks is the
        signal. Unreadable or newer-schema records are skipped with one
        warning each."""
        from .telemetry import steprecord

        prefix = (
            ROLLOUT_TELEMETRY_DIR
            if job is None
            else f"{ROLLOUT_TELEMETRY_DIR}/{_slug(job)}"
        )
        out: List[Dict[str, Any]] = []
        with telemetry.span(
            "catalog.rollout_scan", cat="catalog", path=prefix
        ):
            try:
                paths = _run(self._storage.list_prefix(prefix), self._loop)
            except FileNotFoundError:
                return []
            for p in sorted(paths):
                if not p.endswith(".json"):
                    continue
                try:
                    read_io = ReadIO(path=p)
                    self._storage.sync_read(read_io, self._loop)
                    rec = steprecord.parse_rollout_record(
                        read_io.buf.getvalue()
                    )
                except Exception:  # noqa: BLE001 - degrade, never fail
                    logger.warning(
                        "unreadable rollout record %s under %s (skipped)",
                        p,
                        self.bucket_url,
                        exc_info=True,
                    )
                    continue
                if job is not None and rec.get("job") != job:
                    continue
                out.append(rec)
        return sorted(
            out,
            key=lambda r: (
                r.get("step") or 0,
                r.get("rank", 0),
                r.get("created_unix", 0.0),
            ),
        )

    # --------------------------------------------------------------- load
    def load(self, job: Optional[str] = None) -> List[CatalogRecord]:
        """All readable records, newest last (per-job ``order_key`` order
        interleaved by job), de-duplicated by snapshot name (the newest
        record wins — a re-taken name supersedes its older record).
        Unreadable or newer-schema records are skipped with a warning;
        ``job=`` filters on the record body (not the directory slug)."""
        prefix = RECORD_DIR if job is None else f"{RECORD_DIR}/{_slug(job)}"
        self.last_scan_skipped = 0
        with telemetry.span("catalog.scan", cat="catalog", path=prefix):
            paths = _run(self._storage.list_prefix(prefix), self._loop)
            by_name: Dict[str, CatalogRecord] = {}
            for p in sorted(paths):
                if not p.endswith(".json"):
                    continue
                rec = self._read_record(p)
                if rec is None:
                    self.last_scan_skipped += 1
                    continue
                if job is not None and rec.job != job:
                    continue
                prev = by_name.get(rec.name)
                if prev is None or rec.order_key >= prev.order_key:
                    by_name[rec.name] = rec
        records = sorted(by_name.values(), key=lambda r: r.order_key)
        telemetry.counter_add("catalog.records_scanned", len(records))
        return records

    def _read_record(self, path: str) -> Optional[CatalogRecord]:
        try:
            read_io = ReadIO(path=path)
            self._storage.sync_read(read_io, self._loop)
            rec = CatalogRecord.from_json(read_io.buf.getvalue().decode())
        except Exception:  # noqa: BLE001 - degrade, never fail a scan
            logger.warning(
                "unreadable catalog record %s under %s (skipped)",
                path,
                self.bucket_url,
                exc_info=True,
            )
            return None
        if rec.schema > CATALOG_SCHEMA_VERSION:
            logger.warning(
                "catalog record %s has schema %d > supported %d (skipped; "
                "upgrade this reader)",
                path,
                rec.schema,
                CATALOG_SCHEMA_VERSION,
            )
            return None
        rec.path = path
        return rec

    def latest(self, job: str) -> Optional[CatalogRecord]:
        records = self.load(job=job)
        return records[-1] if records else None

    # --------------------------------------------------------------- pins
    def pins(self) -> Set[str]:
        """Names of pinned snapshots (never condemned by any policy)."""
        out: Set[str] = set()
        try:
            for p in _run(self._storage.list_prefix(PIN_DIR), self._loop):
                try:
                    read_io = ReadIO(path=p)
                    self._storage.sync_read(read_io, self._loop)
                    out.add(str(json.loads(read_io.buf.getvalue())["name"]))
                except Exception:  # noqa: BLE001 - skip unreadable pin
                    logger.warning("unreadable pin %s (skipped)", p)
        except Exception:  # noqa: BLE001 - no pin dir == no pins
            pass
        return out

    def pin(self, name: str) -> None:
        self._storage.sync_write(
            WriteIO(
                path=pin_path(name), buf=json.dumps({"name": name}).encode()
            ),
            self._loop,
        )

    def unpin(self, name: str) -> bool:
        try:
            _run(self._storage.delete(pin_path(name)), self._loop)
            return True
        except FileNotFoundError:
            return False

    # ------------------------------------------------------------ rebuild
    def rebuild(self) -> List[CatalogRecord]:
        """Reconstruct missing records by scanning the bucket for committed
        snapshots: any child tree carrying ``.snapshot_metadata`` that no
        readable record names gets a synthesized record (job unknown →
        ``""``, step parsed from trailing digits of the name, wall time and
        base unknown). Existing records are never rewritten. Returns the
        records written. Memory-backed buckets cannot be scanned (their
        roots are disjoint namespaces) and rebuild returns []."""
        existing = {r.name for r in self.load()}
        written: List[CatalogRecord] = []
        try:
            all_paths = _run(self._storage.list_prefix(""), self._loop)
        except Exception:  # noqa: BLE001 - unlistable bucket: nothing to do
            logger.warning(
                "catalog rebuild: cannot list %s", self.bucket_url,
                exc_info=True,
            )
            return []
        roots = sorted(
            {
                p.partition("/")[0]
                for p in all_paths
                if "/" in p and not p.startswith(f"{CATALOG_DIR}/")
            }
        )
        for root in roots:
            if root in existing:
                continue
            meta_path = f"{root}/{SNAPSHOT_METADATA_FNAME}"
            if meta_path not in all_paths:
                continue
            try:
                from .manifest import SnapshotMetadata

                read_io = ReadIO(path=meta_path)
                self._storage.sync_read(read_io, self._loop)
                metadata = SnapshotMetadata.from_json(
                    read_io.buf.getvalue().decode()
                )
            except Exception:  # noqa: BLE001 - torn metadata: skip
                logger.warning(
                    "catalog rebuild: unreadable metadata for %s (skipped)",
                    root,
                    exc_info=True,
                )
                continue
            m = re.search(r"(\d+)$", root)
            rec = CatalogRecord(
                name=root,
                job="",
                step=int(m.group(1)) if m else -1,
                wall_time=0.0,
                base=None,
                chain_len=0,
                world_size=metadata.world_size,
            )
            if self.append(rec):
                written.append(rec)
        return written


# ---------------------------------------------------------------------------
# Auto-base selection (managed delta chains)
# ---------------------------------------------------------------------------

def auto_base_token(job: str, max_chain_len: int) -> str:
    """The ``base=`` sentinel ``Snapshot.take(job=...)`` plants for the
    preflight round to resolve on rank 0 (one reader, every rank receives
    the same resolved base via the existing preflight broadcast)."""
    return f"{_AUTO_BASE_SCHEME}{max(1, int(max_chain_len))}/{job}"


def is_auto_base(base: Optional[str]) -> bool:
    return bool(base) and str(base).startswith(_AUTO_BASE_SCHEME)


def parse_auto_base(token: str) -> Tuple[str, int]:
    """(job, max_chain_len) from an auto-base token."""
    rest = token[len(_AUTO_BASE_SCHEME):]
    max_str, _, job = rest.partition("/")
    return job, max(1, int(max_str))


def note_commit(bucket_url: str, job: str, name: str, chain_len: int) -> None:
    """Record a just-committed snapshot in the per-process chain cache so
    the next same-job take selects it without storage I/O. Called on EVERY
    rank (the cache is process-local; all ranks hold the same canonical
    path/job from preflight)."""
    _CHAIN_CACHE[(bucket_url, job)] = (name, chain_len)


def invalidate_chain_cache(bucket_url: str) -> None:
    """Drop the bucket's cached chain heads (retention GC may have
    condemned them). A stale survivor would still be safe — the base
    fallback ladder degrades a vanished base to a full snapshot — but
    invalidating keeps steady-state takes on real chains."""
    for key in [k for k in _CHAIN_CACHE if k[0] == bucket_url]:
        _CHAIN_CACHE.pop(key, None)


def resolve_auto_base(
    token: str, snapshot_path: str
) -> Tuple[Optional[str], int]:
    """Resolve an auto-base token against the catalog of ``snapshot_path``'s
    bucket. Returns ``(base_path_or_None, base_chain_len)``:

    - the latest committed same-job snapshot, from the per-process chain
      cache when warm (zero storage I/O in steady state) else a catalog
      scan, as a full path the incremental loader accepts;
    - ``(None, 0)`` — take a FULL snapshot — when the catalog knob is off,
      the bucket has no catalog / no same-job record, the candidate's
      chain is already ``max_chain_len`` deltas deep (the rebase-to-full
      policy), or anything at all fails (fail-open, like every other
      degrade on the base ladder).
    """
    try:
        job, max_chain = parse_auto_base(token)
    except Exception:  # noqa: BLE001 - malformed token: full snapshot
        logger.warning("malformed auto-base token %r; taking a full snapshot",
                       token)
        return None, 0
    if not knobs.is_catalog_enabled():
        return None, 0
    split = split_bucket(snapshot_path)
    if split is None:
        return None, 0
    bucket, _name = split
    cached = _CHAIN_CACHE.get((bucket, job))
    if cached is not None:
        name, chain_len = cached
        if chain_len + 1 > max_chain:
            logger.info(
                "job %s: chain at %s is %d deltas deep (max %d); rebasing "
                "to a full snapshot",
                job, name, chain_len, max_chain,
            )
            return None, 0
        return join_bucket(bucket, name), chain_len
    try:
        with Catalog(bucket) as cat:
            records = cat.load(job=job)
            # Newest first; probe that the candidate is still a committed,
            # present snapshot (retention GC may have condemned it after
            # the record was read — or a crash left a zombie record). A
            # bounded number of probes: an entirely stale chain degrades
            # to a full snapshot rather than an unbounded scan.
            for rec in list(reversed(records))[:3]:
                if _metadata_exists(join_bucket(bucket, rec.name)):
                    note_commit(bucket, job, rec.name, rec.chain_len)
                    if rec.chain_len + 1 > max_chain:
                        logger.info(
                            "job %s: chain at %s is %d deltas deep (max "
                            "%d); rebasing to a full snapshot",
                            job, rec.name, rec.chain_len, max_chain,
                        )
                        return None, 0
                    return join_bucket(bucket, rec.name), rec.chain_len
    except Exception:  # noqa: BLE001 - fail-open: full snapshot
        logger.warning(
            "auto-base selection for job %s under %s failed; taking a "
            "full snapshot",
            job, snapshot_path, exc_info=True,
        )
    return None, 0


def _metadata_exists(snapshot_url: str) -> bool:
    loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(snapshot_url, loop)
        try:
            read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
            storage.sync_read(read_io, loop)
            return True
        except Exception:  # noqa: BLE001 - absent or unreadable: not usable
            return False
        finally:
            storage.sync_close(loop)
    finally:
        loop.close()


def chain_len_of_base(bucket_url: str, base: str) -> int:
    """Chain length this snapshot acquires by building on ``base`` (an
    EXPLICIT ``base=`` whose record may or may not exist): the base's
    recorded chain + 1, or 1 when the base is unrecorded / out-of-bucket
    (conservative: an unknown base is assumed to be a full snapshot)."""
    split = split_bucket(base)
    if split is None or split[0] != bucket_url:
        return 1
    base_name = split[1]
    try:
        with Catalog(bucket_url) as cat:
            for rec in reversed(cat.load()):
                if rec.name == base_name:
                    return rec.chain_len + 1
    except Exception:  # noqa: BLE001 - unknown base: assume full
        pass
    return 1


# ---------------------------------------------------------------------------
# Byte attribution (full vs dedup'd), from checksum sidecars
# ---------------------------------------------------------------------------

def byte_attribution(
    storage: StoragePlugin,
    world_size: int,
    base_url: Optional[str],
    event_loop: asyncio.AbstractEventLoop,
) -> Tuple[int, int, int]:
    """(bytes_total, bytes_written, bytes_deduped) of a just-committed
    snapshot: totals from its own checksum sidecars; the dedup share is
    every object whose (size, content key) also appears in the BASE's
    sidecars — i.e. bytes the chain shares rather than re-stores. No
    collectives: rank 0 computes it alone at append time. (0, 0, 0) when
    sidecars are unavailable (checksums off)."""
    from . import hashing
    from .snapshot import _read_checksum_sidecars

    try:
        merged, _, _ = _read_checksum_sidecars(storage, world_size, event_loop)
    except Exception:  # noqa: BLE001 - attribution is best-effort
        return 0, 0, 0
    base_keys: Set[Tuple[int, str]] = set()
    if base_url:
        loop = asyncio.new_event_loop()
        try:
            base_storage = url_to_storage_plugin_in_event_loop(base_url, loop)
            try:
                from .manifest import SnapshotMetadata

                read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
                base_storage.sync_read(read_io, loop)
                base_world = SnapshotMetadata.from_json(
                    read_io.buf.getvalue().decode()
                ).world_size
                base_merged, _, _ = _read_checksum_sidecars(
                    base_storage, base_world, loop
                )
                for rec in base_merged.values():
                    size = hashing.record_size(rec)
                    if size is None:
                        continue
                    for key in hashing.record_content_keys(rec):
                        base_keys.add((size, key))
            finally:
                base_storage.sync_close(loop)
        except Exception:  # noqa: BLE001 - no base view: all bytes "new"
            base_keys = set()
        finally:
            loop.close()
    total = written = deduped = 0
    for rec in merged.values():
        size = hashing.record_size(rec)
        if size is None:
            continue
        total += size
        if base_keys and any(
            (size, key) in base_keys
            for key in hashing.record_content_keys(rec)
        ):
            deduped += size
        else:
            written += size
    return total, written, deduped


# ---------------------------------------------------------------------------
# Retention policies
# ---------------------------------------------------------------------------

@dataclass
class RetentionPolicy:
    """Parsed retention policy, applied per job. Grammar (comma-separated
    ``key=value`` clauses; see docs/lifecycle.md)::

        last=<K>      keep the newest K snapshots of each job
        hourly=<H>    additionally keep the newest snapshot of each of the
                      last H distinct hours (by record wall time)
        daily=<D>     ...and of each of the last D distinct days
        job=<glob>    restrict the policy to matching job ids (others are
                      fully retained); repeatable

    Pinned snapshots are always retained, whatever the clauses say. A
    policy with no clauses retains everything (the explicit no-op)."""

    last: Optional[int] = None
    hourly: Optional[int] = None
    daily: Optional[int] = None
    job_globs: List[str] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "RetentionPolicy":
        policy = cls()
        spec = (spec or "").strip()
        if not spec:
            return policy
        for clause in spec.split(","):
            clause = clause.strip()
            if not clause:
                continue
            key, sep, value = clause.partition("=")
            if not sep:
                raise ValueError(
                    f"retention clause {clause!r} is not key=value"
                )
            key = key.strip()
            value = value.strip()
            if key in ("last", "hourly", "daily"):
                try:
                    count = int(value)
                except ValueError:
                    raise ValueError(
                        f"retention clause {clause!r}: {value!r} is not an "
                        "integer"
                    ) from None
                if count < 0:
                    raise ValueError(
                        f"retention clause {clause!r}: negative counts are "
                        "meaningless"
                    )
                setattr(policy, key, count)
            elif key == "job":
                policy.job_globs.append(value)
            else:
                raise ValueError(
                    f"unknown retention clause {key!r} (grammar: last=K, "
                    "hourly=H, daily=D, job=<glob>)"
                )
        return policy

    def applies_to(self, job: str) -> bool:
        if not self.job_globs:
            return True
        return any(fnmatch.fnmatch(job, g) for g in self.job_globs)

    def retained(
        self, records: List[CatalogRecord], now: Optional[float] = None
    ) -> Set[str]:
        """Names retained from ONE job's records (any order)."""
        ordered = sorted(records, key=lambda r: r.order_key, reverse=True)
        if self.last is None and self.hourly is None and self.daily is None:
            return {r.name for r in ordered}
        keep: Set[str] = set()
        if self.last:
            keep.update(r.name for r in ordered[: self.last])
        for clause, bucket_s in (("hourly", 3600), ("daily", 86400)):
            count = getattr(self, clause)
            if not count:
                continue
            seen_buckets: Set[int] = set()
            for r in ordered:  # newest first: first hit per bucket wins
                if r.wall_time <= 0:
                    continue  # synthesized/rebuilt record: no wall clock
                b = int(r.wall_time // bucket_s)
                if b not in seen_buckets:
                    seen_buckets.add(b)
                    keep.add(r.name)
                if len(seen_buckets) >= count:
                    break
        return keep


@dataclass
class RetentionPlan:
    """What a policy run would keep and collect."""

    retained: List[str]
    condemned: List[str]
    pinned: List[str]
    by_job: Dict[str, Dict[str, List[str]]]


def plan_retention(
    records: List[CatalogRecord],
    pins: Set[str],
    policy: RetentionPolicy,
    now: Optional[float] = None,
) -> RetentionPlan:
    """Apply ``policy`` per job over the catalog. Pins always retain; jobs
    the policy's ``job=`` globs exclude are fully retained. Condemned =
    recorded, committed-at-record-time snapshots the policy drops — any
    PREFIX of a chain may land here: snapshots are self-contained (see the
    module docstring), so collecting a retained delta's base never breaks
    the delta's restore."""
    by_job: Dict[str, List[CatalogRecord]] = {}
    for r in records:
        by_job.setdefault(r.job, []).append(r)
    retained: Set[str] = set()
    per_job: Dict[str, Dict[str, List[str]]] = {}
    for job, recs in sorted(by_job.items()):
        if not policy.applies_to(job):
            kept = {r.name for r in recs}
        else:
            kept = policy.retained(recs, now=now)
        kept |= pins & {r.name for r in recs}
        retained |= kept
        per_job[job] = {
            "retained": sorted(kept),
            "condemned": sorted({r.name for r in recs} - kept),
        }
    all_names = {r.name for r in records}
    condemned = sorted(all_names - retained)
    return RetentionPlan(
        retained=sorted(retained),
        condemned=condemned,
        pinned=sorted(pins & all_names),
        by_job=per_job,
    )


def validate_chain_closure(
    bucket_url: str,
    retained: List[str],
    condemned: List[str],
) -> None:
    """The chain-aware safety check run before any retention deletion:
    every storage object a RETAINED snapshot's manifest references must
    live under a retained root. Today that holds structurally (manifest
    locations are snapshot-root-relative; dedup materializes shared
    objects under every root as hard links / server-side copies), so this
    walk is a cheap invariant re-check — but a future layout that stored
    chain-shared objects once, outside the deltas, would trip it HERE
    instead of silently tearing a retained snapshot's restore. Raises
    ``RuntimeError`` naming the violating references."""
    from .manifest import SnapshotMetadata
    from .snapshot import _manifest_storage_locations

    condemned_set = set(condemned)
    violations: List[str] = []
    loop = asyncio.new_event_loop()
    try:
        for name in retained:
            url = join_bucket(bucket_url, name)
            try:
                storage = url_to_storage_plugin_in_event_loop(url, loop)
                try:
                    read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
                    storage.sync_read(read_io, loop)
                    metadata = SnapshotMetadata.from_json(
                        read_io.buf.getvalue().decode()
                    )
                finally:
                    storage.sync_close(loop)
            except Exception:  # noqa: BLE001 - unreadable retained manifest
                # Retention must not delete anything whose keep-set it
                # cannot compute; the caller surfaces this as a hard error.
                raise RuntimeError(
                    f"retention: cannot read the manifest of retained "
                    f"snapshot {name!r} under {bucket_url} — refusing to "
                    "collect anything"
                ) from None
            for loc in _manifest_storage_locations(metadata.manifest):
                # Locations are root-relative by construction; an absolute
                # or parent-escaping location would reach outside this
                # snapshot's root — exactly what a condemned-prefix delete
                # could tear.
                if loc.startswith(("/", "..")) or any(
                    loc.startswith(f"{c}/") for c in condemned_set
                ):
                    violations.append(f"{name}: {loc}")
    finally:
        loop.close()
    if violations:
        raise RuntimeError(
            "retention: retained snapshots reference objects outside their "
            "own roots (collecting the condemned set would tear them): "
            + "; ".join(sorted(violations)[:8])
        )


def retain(
    bucket_url: str,
    policy: RetentionPolicy,
    dry_run: bool = True,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """The retention engine: plan per-job retention over the catalog,
    validate chain closure, and drive :meth:`Snapshot.gc`'s shared
    deletion path with the explicit keep-set. Only RECORDED snapshots are
    ever condemned, and uncommitted record-less trees are left alone
    (in-flight takes are indistinguishable from crashes here — the plain
    whole-bucket ``Snapshot.gc`` reclaims those, with its documented
    don't-run-concurrently caveat). Returns the gc report extended with
    the plan."""
    from .snapshot import Snapshot

    with Catalog(bucket_url) as cat:
        records = cat.load()
        pins = cat.pins()
        skipped = getattr(cat, "last_scan_skipped", 0)
    if skipped:
        # Unreadable records are fail-open at the SAFE end: their
        # snapshots cannot be condemned (gc only condemns roots in the
        # record universe) — the bucket over-retains until the records
        # are readable again or rebuilt.
        logger.warning(
            "retention under %s: %d catalog record(s) unreadable — their "
            "snapshots are implicitly retained this run (rebuild the "
            "catalog to reclaim them)",
            bucket_url,
            skipped,
        )
    plan = plan_retention(records, pins, policy, now=now)
    if plan.condemned:
        validate_chain_closure(bucket_url, plan.retained, plan.condemned)
    report = Snapshot.gc(
        bucket_url,
        dry_run=dry_run,
        keep_roots=set(plan.retained) | pins,
        roots=[r.name for r in records],
        collect_debris=False,
    )
    report["policy"] = {
        "retained": plan.retained,
        "condemned": plan.condemned,
        "pinned": plan.pinned,
        "by_job": plan.by_job,
    }
    if not dry_run:
        telemetry.counter_add("gc.roots_condemned", len(plan.condemned))
        # Cached chain heads may be among the condemned; the next
        # auto-base take re-reads the catalog.
        invalidate_chain_cache(bucket_url)
    return report


def chain_of(
    records: List[CatalogRecord], name: str
) -> List[CatalogRecord]:
    """The base chain ending at ``name``, oldest first, as far back as the
    records reach (display/diagnostics — restore never walks this)."""
    by_name = {r.name: r for r in records}
    chain: List[CatalogRecord] = []
    cur = by_name.get(name)
    seen: Set[str] = set()
    while cur is not None and cur.name not in seen:
        seen.add(cur.name)
        chain.append(cur)
        cur = by_name.get(cur.base) if cur.base else None
    return list(reversed(chain))
