"""Core contracts tying planning to execution to storage.

TPU-native analogue of the reference's ``io_types.py`` (see
``/root/reference/torchsnapshot/io_types.py:19-103``): the planning layer turns
application state into :class:`WriteReq`/:class:`ReadReq` lists, the scheduler
executes them against a :class:`StoragePlugin`, and buffers flow through the
:class:`BufferStager`/:class:`BufferConsumer` protocols so that device-to-host
transfer, serialization, and storage I/O can be pipelined without ever
materializing more than a memory budget's worth of data.
"""

from __future__ import annotations

import abc
import asyncio
import io
from concurrent.futures import Executor
from dataclasses import dataclass, field
from typing import AsyncIterator, List, Optional, Tuple, Union

# A staged buffer is either raw bytes or a zero-copy view over host memory.
BufferType = Union[bytes, bytearray, memoryview]


class BufferStager(abc.ABC):
    """Produces the bytes for one write request, as lazily as possible.

    ``stage_buffer`` performs the expensive part (device-to-host transfer +
    serialization). It is invoked by the scheduler only when the memory budget
    admits the request, and runs its blocking portions on ``executor``.

    Stagers that can produce their bytes *incrementally* additionally
    implement the streaming protocol (:meth:`can_stream` /
    :meth:`stage_chunks`): the scheduler then overlaps the storage write of
    chunk *k* with the D2H/serialization of chunk *k+1* within one request,
    and debits/credits the memory budget per chunk instead of per request.
    """

    # True for stagers whose ``stage_chunks`` yields views into one host
    # buffer that stays alive until the stream ends (e.g. a device-packed
    # slab fetched in a single D2H): the scheduler then keeps the full
    # staging cost debited for the stream's lifetime instead of pretending
    # per-chunk credits free memory that is still held.
    stream_holds_full_buffer = False

    @abc.abstractmethod
    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        ...

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Estimated peak host memory consumed by :meth:`stage_buffer`."""
        ...

    def can_stream(self) -> bool:
        """Whether :meth:`stage_chunks` yields more than one chunk AND
        streaming preserves capture semantics for this request's source
        (immutable device data, a private host capture, or a sync take —
        a streamed request's source is read until its last chunk stages,
        long after an async take's capture point)."""
        return False

    async def stage_chunks(
        self, executor: Optional[Executor] = None
    ) -> AsyncIterator[BufferType]:
        """Yield the request's bytes as ordered chunks whose concatenation
        is exactly what :meth:`stage_buffer` would have returned. Default:
        one chunk (the whole buffer) — only meaningful when
        :meth:`can_stream` is True."""
        yield await self.stage_buffer(executor)

    def start_d2h_hint(self) -> None:
        """Optionally begin the device→host transfer early (non-blocking).

        Called by ``_take_impl`` on deferred-staging requests that survived
        write partitioning, right before ``async_take`` returns — so DMAs for
        exactly the bytes this rank will write start overlapping training.
        Default: no-op (host-resident sources have nothing to transfer).
        """


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager
    # Async snapshots may defer this request's staging past async_take's
    # return (device arrays: immutable + defensively forked, so nothing can
    # invalidate them). Mutable host state leaves this False and is staged
    # before async_take returns, under the memory budget — the reference's
    # capture semantics (``scheduler.py:178-214``).
    defer_staging: bool = False


class BufferConsumer(abc.ABC):
    """Consumes the bytes of one read request (deserialize + copy into place)."""

    @abc.abstractmethod
    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        ...

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Estimated peak host memory consumed by :meth:`consume_buffer`."""
        ...


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    byte_range: Optional[Tuple[int, int]] = None  # [begin, end)


@dataclass
class WriteIO:
    path: str
    buf: BufferType
    # want_digest: set by the caller when it will consume digest_out —
    # plugins that can compute the object digest inside their write path
    # ([crc32, size, sha256-hex | None], the sidecar format) then fill
    # digest_out; the native FS engine hashes chunk-by-chunk while the data
    # is cache-hot, sparing the scheduler's Python hashing pass its full
    # extra memory sweep. Writes whose caller hashes elsewhere (incremental
    # takes pre-hash for dedup; sidecar files) leave want_digest False so
    # no plugin wastes a pass. digest_out None = not computed.
    want_digest: bool = False
    digest_out: Optional[list] = None


@dataclass
class ReadIO:
    path: str
    byte_range: Optional[Tuple[int, int]] = None
    buf: io.BytesIO = field(default_factory=io.BytesIO)


class StorageWriteStream(abc.ABC):
    """An in-progress streamed write of ONE storage object.

    Obtained from :meth:`StoragePlugin.write_stream`. ``append`` calls are
    sequential (never concurrent for one stream) and deliver the object's
    bytes in order; ``commit`` makes the object visible atomically —
    a stream that is aborted (or never committed) must leave no object at
    the path. Exactly one of ``commit``/``abort`` ends the stream.
    """

    @abc.abstractmethod
    async def append(self, buf: BufferType) -> None:
        ...

    @abc.abstractmethod
    async def commit(self) -> None:
        ...

    @abc.abstractmethod
    async def abort(self) -> None:
        ...


class BufferedWriteStream(StorageWriteStream):
    """Fallback :class:`StorageWriteStream`: accumulate appends in host RAM
    and issue one plain ``write`` at commit. Correct for any plugin (atomic
    visibility rides on ``write``'s own guarantees) but holds the whole
    object in memory — plugins advertise true incremental appends by
    setting ``supports_streaming = True`` and overriding ``write_stream``;
    the scheduler only routes requests through streams on those.

    Appended buffers are retained AS-IS (zero-copy: a memoryview keeps its
    backing host buffer alive until commit/abort, matching the stream
    contract that appended bytes are immutable until the stream ends) and
    joined once at commit."""

    def __init__(self, storage: "StoragePlugin", path: str) -> None:
        self._storage = storage
        self._path = path
        self._chunks: list = []

    async def append(self, buf: BufferType) -> None:
        self._chunks.append(buf)

    async def commit(self) -> None:
        await self._storage.write(
            WriteIO(path=self._path, buf=b"".join(self._chunks))
        )
        self._chunks = []

    async def abort(self) -> None:
        self._chunks = []


class StoragePlugin(abc.ABC):
    """Async storage backend contract (reference ``io_types.py:67-103``).

    Implementations must be safe for many concurrent in-flight operations on
    one event loop. Ranged reads (``ReadIO.byte_range``) enable random access
    into cloud-resident snapshots without fetching whole objects.

    **Absence contract**: ``read`` of an object that does not exist raises
    :class:`FileNotFoundError` — each plugin normalizes its backend's absence
    error (ENOENT, GCS ``NotFound``, S3 ``NoSuchKey``) so callers never sniff
    backend-specific exception names or messages. ``delete`` of an absent
    object either succeeds silently (idempotent backends like S3) or raises
    :class:`FileNotFoundError`; it never raises a backend-specific absence
    error.
    """

    # Local-disk backends set this True so the scheduler's default IO
    # concurrency divides across co-hosted ranks (they share one device);
    # network/object stores keep the full default (latency-hiding
    # concurrency, not seek-bound).
    scales_io_with_local_world = False

    # True when ``write_stream`` appends incrementally (bytes leave host RAM
    # as they are appended): fs (positioned writes into a temp file), memory
    # (growing buffer), gcs (resumable session), s3 (multipart parts). The
    # scheduler's streamed-request path is gated on this flag — the
    # :class:`BufferedWriteStream` default would silently hold the whole
    # object in RAM, defeating the per-chunk budget accounting.
    supports_streaming = False

    @abc.abstractmethod
    async def write(self, write_io: WriteIO) -> None:
        ...

    async def write_stream(self, path: str) -> StorageWriteStream:
        """Open a streamed write of one object at ``path`` (see
        :class:`StorageWriteStream`). Default: a buffered fallback that
        degenerates to one ``write`` at commit."""
        return BufferedWriteStream(self, path)

    @abc.abstractmethod
    async def read(self, read_io: ReadIO) -> None:
        ...

    @abc.abstractmethod
    async def delete(self, path: str) -> None:
        ...

    async def link_in(self, src_abs_path: str, path: str) -> bool:
        """Optionally alias an existing file at absolute ``src_abs_path``
        into this store at ``path`` without copying bytes (incremental
        snapshots). Returns False when unsupported or failed — the caller
        falls back to a normal write. Default: unsupported."""
        return False

    async def list_prefix(self, prefix: str) -> List[str]:
        """All object paths under ``prefix`` (relative to the plugin root,
        ``""`` = everything). The substrate of ``Snapshot.gc``: debris from
        torn takes can only be reclaimed on backends that can enumerate it.
        Built-in plugins all implement this; third-party plugins that don't
        simply can't be garbage-collected."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support listing; Snapshot.gc "
            "requires a plugin with list_prefix"
        )

    async def prune_empty(self) -> None:
        """Remove now-empty directories after deletions, where the backend
        has real directories (fs). Object stores have none: default no-op."""

    async def close(self) -> None:
        pass

    # -- sync conveniences driving a caller-owned event loop -----------------
    def sync_write(
        self, write_io: WriteIO, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.write(write_io), event_loop)

    def sync_read(
        self, read_io: ReadIO, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.read(read_io), event_loop)

    def sync_close(
        self, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.close(), event_loop)


def _run(coro, event_loop: Optional[asyncio.AbstractEventLoop]) -> None:
    if event_loop is not None:
        event_loop.run_until_complete(coro)
    else:
        asyncio.new_event_loop().run_until_complete(coro)
