"""Value -> (Entry, WriteReqs/ReadReqs) dispatch.

TPU-native analogue of the reference's ``io_preparer.py:51-178``, with the
routing redesigned around ``jax.Array``'s sharding metadata instead of
torch's type taxonomy:

- primitives -> inline :class:`PrimitiveEntry`;
- ``jax.Array`` **fully replicated across every process** -> the replicated
  array path (saved once globally, write load split by the partitioner).
  This replaces the reference's DDP-module sniffing
  (``snapshot.py:828-844``): on TPU, replication is *read off the sharding*,
  no user globs required;
- ``jax.Array`` on exactly one local device -> per-rank array path;
- any other ``jax.Array`` (sharded / partially replicated) -> the sharded
  path (elastic by construction);
- ``np.ndarray`` -> array path (replicated only via user glob);
- anything else -> pickled object.

Arrays whose serialized size exceeds the chunking knob are split into dim-0
chunks for transfer/I-O pipelining.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .io_types import WriteReq
from .manifest import (
    Manifest,
    PrimitiveEntry,
    PRIMITIVE_TYPES,
)
from .io_preparers.array import ArrayIOPreparer
from .io_preparers.chunked_array import ChunkedArrayIOPreparer, should_chunk
from .io_preparers.object import ObjectIOPreparer
from .io_preparers.sharded_array import ShardedArrayIOPreparer
from .utils import knobs
from .utils.lru import BoundedLRU

logger = logging.getLogger(__name__)


def get_storage_path(logical_path: str, rank: int, replicated: bool) -> str:
    """Reference ``io_preparer.py:51-57`` (``sharded/`` handled separately)."""
    return f"replicated/{logical_path}" if replicated else f"{rank}/{logical_path}"


def _is_jax_array(obj: Any) -> bool:
    import jax

    return isinstance(obj, jax.Array)


def _globally_replicated(arr: Any, world_size: int) -> bool:
    sharding = arr.sharding
    if not sharding.is_fully_replicated:
        return False
    procs = {d.process_index for d in sharding.device_set}
    return len(procs) == world_size and world_size > 1


class _HostShard:
    """Mimics ``jax.Shard`` for host-captured data: the planning-visible
    metadata (index/replica_id/device) with the data already in host RAM."""

    __slots__ = ("index", "replica_id", "device", "data")

    def __init__(self, index: Any, replica_id: int, device: Any, data: np.ndarray) -> None:
        self.index = index
        self.replica_id = replica_id
        self.device = device
        self.data = data


class HostCapturedArray:
    """A donation-safe *host* capture of a ``jax.Array``.

    Produced by the degraded async-fork path when HBM can't hold an
    on-device defensive copy (the reference's host-capture semantics,
    ``io_preparers/tensor.py:254-278`` — which always work, at the cost of a
    blocking D2H inside the take stall). Preserves exactly the metadata the
    write planners read — ``shape``/``dtype``/``sharding``/
    ``addressable_shards`` with per-shard ``index``/``replica_id`` — so the
    resulting plan (entries, shard locations, partition assignment) is
    byte-identical to the device-forked plan; only the stagers' data source
    differs (private host buffers instead of forked device buffers).
    """

    def __init__(self, shape: Tuple[int, ...], dtype: Any, sharding: Any, shards: List[_HostShard]) -> None:
        self.shape = shape
        self.dtype = dtype
        self.sharding = sharding
        self.addressable_shards = shards

    def assembled_local(self) -> np.ndarray:
        """The full local value (what ``np.asarray`` yields for the original
        array): shard 0 when one shard covers the array, else the shards
        scattered into a host buffer (a per-rank array sharded across
        multiple *local* devices classifies as "array" and stages whole)."""
        shards = self.addressable_shards
        if len(shards) == 1 or self.sharding.is_fully_replicated:
            return shards[0].data
        out = np.empty(self.shape, dtype=self.dtype)
        for s in shards:
            out[s.index] = s.data
        return out


def _is_plannable_array(value: Any) -> bool:
    """jax.Array, or a host capture carrying the same planning metadata."""
    return _is_jax_array(value) or isinstance(value, HostCapturedArray)


def classify(value: Any, world_size: int) -> str:
    """One of: primitive | sharded | replicated_array | array | object."""
    if isinstance(value, PRIMITIVE_TYPES) and not isinstance(value, np.generic):
        return "primitive"
    if _is_plannable_array(value):
        if _globally_replicated(value, world_size):
            return "replicated_array"
        procs = {d.process_index for d in value.sharding.device_set}
        if world_size > 1 and len(procs) == 1:
            # Device set confined to one process: this is per-rank data, not
            # a slice of a global array. The sharded path would write it to
            # rank-less ``sharded/<path>`` locations where different ranks'
            # distinct arrays at the same logical path clobber each other.
            return "array"
        if len(value.sharding.device_set) == 1:
            return "array"
        return "sharded"
    if isinstance(value, np.ndarray):
        return "array"
    return "object"


def _defensive_device_copies(arrs: List[Any]) -> List[Any]:
    """Fork jax arrays' device buffers for async capture — in ONE program.

    TPU-native replacement for the reference's defensive *host* copies
    (``io_preparers/tensor.py:254-278``): torch must capture mutable tensors
    in host RAM before ``async_take`` returns; jax arrays are immutable, so
    the only hazard is the training step *donating* the buffers
    (``donate_argnums``), which marks every reference deleted. An on-device
    copy (dispatched asynchronously — microseconds on the host timeline,
    HBM-bandwidth on the device) detaches the snapshot from donation.

    All leaves are copied in a single jitted call: per-leaf ``jit(jnp.copy)``
    would compile one XLA program per (sharding, shape) — tens of seconds of
    cold-start stall on a real transformer state — whereas one program
    compiles once per state *structure* and dispatches once per take.

    The copy runs under ``jit`` pinned to each array's own sharding: eager
    ``jnp.copy`` would raise on non-fully-addressable (multi-process) global
    arrays, and every rank reaches this point in the same gathered-key
    order, so the SPMD requirement holds. ``out_shardings`` is explicit —
    downstream routing (``classify``, shard enumeration) reads the copy's
    sharding, so propagation must not be allowed to pick a different one.

    One jitted computation requires all operands to share a device
    assignment, so leaves are grouped by assignment first (params on the
    full mesh vs. a step counter committed to one device vs. host-offloaded
    state); each group compiles and dispatches once.

    **HBM-pressure degradation** (the availability guarantee): exactly when
    checkpointing matters most — model + optimizer near HBM capacity — the
    full-state copy may not fit. An allocation failure
    (``RESOURCE_EXHAUSTED``) from a group's fork degrades that group by
    bisection: sub-groups whose fork still fits stay device-forked (their
    D2H drains asynchronously in the background as usual), and leaves whose
    fork fails even alone are captured *through host RAM, blocking, from
    the original buffers* — zero HBM overhead, donation-safe because it
    completes before ``async_take`` returns. This is the reference's
    host-capture design (``io_preparers/tensor.py:254-278``), applied only
    to the residual that doesn't fit, so ``async_take`` is never less
    available than the reference: the HBM overhead is bounded by what
    actually fit (by construction), and only the host-captured bytes extend
    the stall (a warning reports both).
    """
    groups: Dict[Any, List[int]] = {}
    for i, a in enumerate(arrs):
        groups.setdefault(_device_assignment_key(a.sharding), []).append(i)
    out: List[Any] = [None] * len(arrs)
    # Cumulative successfully-forked local bytes across this take, for the
    # simulated-HBM-limit knob (mirrors real accounting: forks accumulate).
    forked_bytes = [0]
    captured: List[Any] = []  # host-captured leaves, for the warning
    for indices in groups.values():
        group = [arrs[i] for i in indices]
        copies = _fork_or_capture(group, forked_bytes, captured)
        for i, c in zip(indices, copies):
            out[i] = c
    if captured:
        total = sum(_local_fork_nbytes(a) for a in captured)
        logger.warning(
            "async_take defensive fork hit HBM pressure: %d of %d leaves "
            "(%.3f GB) were captured through host RAM instead (blocking "
            "D2H inside the take stall; device-forked leaves still drain "
            "in the background). The snapshot remains donation-safe.",
            len(captured),
            len(arrs),
            total / 1e9,
        )
    return out


def _local_fork_nbytes(arr: Any) -> int:
    """HBM bytes a defensive fork of ``arr`` allocates on this process."""
    return sum(int(s.data.nbytes) for s in arr.addressable_shards)


def _is_oom_error(e: BaseException) -> bool:
    s = str(e)
    return "RESOURCE_EXHAUSTED" in s or "out of memory" in s.lower()


# Log-once guard for the backend-capability degradation below.
_fork_unsupported_warned = False


def _is_fork_unsupported_error(e: BaseException) -> bool:
    """The batched copy is impossible on this backend — notably jax's CPU
    backend, which refuses multiprocess jitted computations outright
    (INVALID_ARGUMENT), regardless of size. Bisection can't help; the whole
    group must capture through host RAM (the reference's design, still
    donation-safe)."""
    s = str(e)
    return "implemented on the CPU backend" in s


def _try_fork(group: List[Any], forked_bytes: List[int]) -> List[Any]:
    """One batched jitted copy of ``group``; raises on allocation failure.

    PJRT allocates output buffers synchronously at dispatch, so a real
    ``RESOURCE_EXHAUSTED`` surfaces from this call without blocking on the
    copy itself. The knob simulates the same failure for tests/tiny-HBM."""
    limit = knobs.get_async_fork_hbm_limit_bytes()
    if limit is not None:
        need = sum(_local_fork_nbytes(a) for a in group)
        if forked_bytes[0] + need > limit:
            raise RuntimeError(
                f"RESOURCE_EXHAUSTED: simulated HBM limit "
                f"({forked_bytes[0]} + {need} > {limit} bytes)"
            )
    copies = _batch_copy_fn(tuple(a.sharding for a in group))(group)
    if limit is not None:
        # Accounting feeds only the simulated limit; skip the per-shard
        # walk on the production hot path.
        forked_bytes[0] += need
    return copies


# Bisection depth bound for the degraded fork: each distinct sub-group is a
# fresh XLA program whose compile runs inside the (already degraded) stall,
# so recursion stops at quarters — at most 6 extra compiles per failing
# group, reused across takes via the _BATCH_COPIES LRU. Anything a quarter
# group can't fit is host-captured without further compile attempts. (The
# simulated-limit knob raises before compiling, so tests pay nothing.)
_MAX_FORK_BISECT_DEPTH = 2


def _fork_or_capture(
    group: List[Any], forked_bytes: List[int], captured: List[Any], depth: int = 0
) -> List[Any]:
    """Fork the group; on allocation failure bisect so what fits stays
    device-forked and the rest is host-captured (see
    ``_defensive_device_copies``)."""
    try:
        return _try_fork(group, forked_bytes)
    except Exception as e:  # noqa: BLE001 - only OOM/capability degrades
        if _is_fork_unsupported_error(e):
            global _fork_unsupported_warned
            if not _fork_unsupported_warned:
                _fork_unsupported_warned = True
                logger.warning(
                    "async_take defensive device fork is unsupported on "
                    "this backend (%s); capturing through host RAM instead "
                    "— donation-safe, but the blocking D2H joins the take "
                    "stall",
                    e,
                )
            return _host_capture_group(group)
        if not _is_oom_error(e):
            raise
    if len(group) == 1 or depth >= _MAX_FORK_BISECT_DEPTH:
        captured.extend(group)
        return _host_capture_group(group)
    mid = len(group) // 2
    return _fork_or_capture(
        group[:mid], forked_bytes, captured, depth + 1
    ) + _fork_or_capture(group[mid:], forked_bytes, captured, depth + 1)


def _host_capture_group(group: List[Any]) -> List[HostCapturedArray]:
    """Blocking host capture of a group of arrays: async D2H hints for EVERY
    shard of EVERY array first, so the per-shard resolves pipeline on the
    transfer engine instead of serializing array by array."""
    from .io_preparers.array import hint_copy_to_host

    for a in group:
        for s in a.addressable_shards:
            hint_copy_to_host(s.data)
    return [_host_capture(a) for a in group]


def _aliases_device_buffer(shard_data: Any) -> bool:
    """Whether ``np.asarray(shard_data)`` may alias the XLA buffer (which
    donation would then free under the stager). A TPU device-memory D2H
    result is always a private host copy; CPU-backed and host-offloaded
    arrays can be zero-copy views — and jax returns its cached ``np.asarray``
    read-only with ``base=None`` on every backend, so the numpy flags can't
    distinguish the two."""
    try:
        if next(iter(shard_data.devices())).platform == "cpu":
            return True
        return shard_data.sharding.memory_kind not in (None, "device")
    except Exception:  # pragma: no cover - be safe on exotic platforms
        return True


def _host_capture(arr: Any) -> HostCapturedArray:
    host_shards = []
    for s in arr.addressable_shards:
        data = np.asarray(s.data)
        if _aliases_device_buffer(s.data):
            data = data.copy()
        host_shards.append(_HostShard(s.index, s.replica_id, s.device, data))
    return HostCapturedArray(
        tuple(int(d) for d in arr.shape), np.dtype(arr.dtype), arr.sharding, host_shards
    )


def _device_assignment_key(sharding) -> Any:
    try:
        return tuple(d.id for d in sharding._device_assignment)
    except AttributeError:
        # Not part of jax's public API. Fall back to one group per distinct
        # sharding: equal shardings trivially share an assignment, while a
        # set-based key would merge same-device-set/different-order
        # assignments into one jit call, which jax rejects. Costs batching
        # granularity, never correctness.
        return sharding


def _batch_copy_fn(shardings: Tuple[Any, ...]):
    def build():
        import jax
        import jax.numpy as jnp

        return jax.jit(
            lambda xs: [jnp.copy(x) for x in xs], out_shardings=list(shardings)
        )

    return _BATCH_COPIES.get_or_build(shardings, build)


_BATCH_COPIES = BoundedLRU()


def capture_flattened(
    flattened: Dict[str, Any], timings: Optional[Dict[str, float]] = None
) -> Dict[str, Any]:
    """The async-take capture step, shared by the full prepare path and the
    prepared-cache rebind path (``prepare_cache.py``): detach device arrays
    from the training step before ``async_take`` returns.

    Under the default ``fork`` capture mode this dispatches the defensive
    on-device copies (donation safety — see ``_defensive_device_copies``).
    Under ``donate`` (``TORCHSNAPSHOT_TPU_ASYNC_CAPTURE=donate``) the
    caller has promised not to donate or delete the passed arrays until
    the snapshot commits, so the immutable arrays are captured ZERO-COPY:
    no fork, no HBM overhead, capture cost ~0 — the steady-state mode.

    Returns ``flattened`` with device leaves replaced by their captures
    (the input dict is never mutated); ``timings["d2h_hint"]`` accumulates
    the capture wall time."""
    device_paths = [p for p, v in flattened.items() if _is_jax_array(v)]
    if (
        not device_paths
        or not knobs.is_async_device_copy_enabled()
        or knobs.get_async_capture_mode() == "donate"
    ):
        return flattened
    t0 = time.monotonic()
    copies = _defensive_device_copies([flattened[p] for p in device_paths])
    if timings is not None:
        timings["d2h_hint"] = timings.get("d2h_hint", 0.0) + (
            time.monotonic() - t0
        )
    flattened = dict(flattened)
    flattened.update(zip(device_paths, copies))
    return flattened


def prepare_write(
    flattened: Dict[str, Any],
    rank: int,
    world_size: int,
    replicated_paths: Set[str],
    is_async_snapshot: bool = False,
    timings: Optional[Dict[str, float]] = None,
    leaf_index: Optional[Dict[str, List[WriteReq]]] = None,
) -> Tuple[Manifest, List[WriteReq]]:
    """Plan all writes for this rank's flattened state (no data moves yet).

    ``timings``: optional out-param decomposing this call's wall time into
    the ``stage.prepare.*`` buckets — ``d2h_hint`` (the defensive device
    fork + transfer hints), ``stager_construction`` (the per-preparer
    ``prepare_write`` calls building stagers/manifest entries), and
    ``plan`` (classification, path mapping, everything else). The take
    path persists them as sub-spans of the ``prepare_write`` stall phase,
    so the stall decomposition's dominant phase is attributable instead of
    a single opaque number.

    ``leaf_index``: optional out-param mapping each logical path to the
    write requests its leaf produced, in construction order — the
    prepared-state cache's rebind map (``prepare_cache.py``). Primitives
    record an empty list (manifest entry only)."""
    t_begin = time.monotonic()
    d2h_hint_s = 0.0
    stager_s = 0.0
    manifest: Manifest = {}
    write_reqs: List[WriteReq] = []
    if is_async_snapshot:
        # Device arrays are immutable; fork them against donation (or
        # capture them zero-copy under the donate contract) and defer
        # their staging past async_take's return. Mutable host state keeps
        # defer_staging=False and is captured (staged under the budget)
        # before async_take returns — the reference's semantics
        # (``scheduler.py:178-214``).
        capture_timings: Dict[str, float] = {}
        flattened = capture_flattened(flattened, capture_timings)
        d2h_hint_s += capture_timings.get("d2h_hint", 0.0)
    device_paths_set = {p for p, v in flattened.items() if _is_plannable_array(v)}
    for logical_path, value in flattened.items():
        is_device_value = logical_path in device_paths_set
        kind = classify(value, world_size)
        glob_replicated = logical_path in replicated_paths
        # Host-captured leaves already hold private host buffers: their
        # stagers must not re-copy (is_async_snapshot=False below), but
        # their staging still defers past async_take's return like any
        # other immutable capture.
        is_captured = isinstance(value, HostCapturedArray)

        if kind == "primitive":
            manifest[logical_path] = PrimitiveEntry.from_value(
                value, replicated=glob_replicated
            )
            if leaf_index is not None:
                leaf_index[logical_path] = []
            continue

        if kind == "sharded":
            t0 = time.monotonic()
            entry, reqs = ShardedArrayIOPreparer.prepare_write(
                logical_path,
                value,
                is_async_snapshot=is_async_snapshot and not is_captured,
            )
            stager_s += time.monotonic() - t0
            manifest[logical_path] = entry
            if is_async_snapshot:
                for r in reqs:
                    r.defer_staging = True
            if leaf_index is not None:
                leaf_index[logical_path] = list(reqs)
            write_reqs.extend(reqs)
            continue

        if kind in ("replicated_array", "array"):
            replicated = kind == "replicated_array" or glob_replicated
            arr = value
            if is_captured:
                arr = arr.assembled_local()
            elif (
                _is_jax_array(arr)
                and len(arr.sharding.device_set) > 1
                and arr.sharding.is_fully_replicated
            ):
                # Fully-replicated multi-device array: stage from the local copy.
                arr = arr.addressable_shards[0].data
            storage_path = get_storage_path(logical_path, rank, replicated)
            t0 = time.monotonic()
            if should_chunk(arr):
                entry, reqs = ChunkedArrayIOPreparer.prepare_write(
                    storage_path, arr, replicated, is_async_snapshot and not is_captured
                )
            else:
                entry, reqs = ArrayIOPreparer.prepare_write(
                    storage_path, arr, replicated, is_async_snapshot and not is_captured
                )
            stager_s += time.monotonic() - t0
            manifest[logical_path] = entry
            if is_async_snapshot and is_device_value:
                for r in reqs:
                    r.defer_staging = True
            if leaf_index is not None:
                leaf_index[logical_path] = list(reqs)
            write_reqs.extend(reqs)
            continue

        # object fallback
        storage_path = get_storage_path(logical_path, rank, glob_replicated)
        t0 = time.monotonic()
        entry, reqs = ObjectIOPreparer.prepare_write(
            storage_path, value, replicated=glob_replicated
        )
        stager_s += time.monotonic() - t0
        manifest[logical_path] = entry
        if leaf_index is not None:
            leaf_index[logical_path] = list(reqs)
        write_reqs.extend(reqs)
    if timings is not None:
        total = time.monotonic() - t_begin
        timings["d2h_hint"] = d2h_hint_s
        timings["stager_construction"] = stager_s
        # Classification, path mapping, manifest assembly — the remainder.
        timings["plan"] = max(0.0, total - d2h_hint_s - stager_s)
    return manifest, write_reqs
