"""Value -> (Entry, WriteReqs/ReadReqs) dispatch.

TPU-native analogue of the reference's ``io_preparer.py:51-178``, with the
routing redesigned around ``jax.Array``'s sharding metadata instead of
torch's type taxonomy:

- primitives -> inline :class:`PrimitiveEntry`;
- ``jax.Array`` **fully replicated across every process** -> the replicated
  array path (saved once globally, write load split by the partitioner).
  This replaces the reference's DDP-module sniffing
  (``snapshot.py:828-844``): on TPU, replication is *read off the sharding*,
  no user globs required;
- ``jax.Array`` on exactly one local device -> per-rank array path;
- any other ``jax.Array`` (sharded / partially replicated) -> the sharded
  path (elastic by construction);
- ``np.ndarray`` -> array path (replicated only via user glob);
- anything else -> pickled object.

Arrays whose serialized size exceeds the chunking knob are split into dim-0
chunks for transfer/I-O pipelining.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .io_types import WriteReq
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    Manifest,
    ObjectEntry,
    PrimitiveEntry,
    PRIMITIVE_TYPES,
)
from .io_preparers.array import ArrayIOPreparer
from .io_preparers.chunked_array import ChunkedArrayIOPreparer, should_chunk
from .io_preparers.object import ObjectIOPreparer
from .io_preparers.sharded_array import ShardedArrayIOPreparer
from .utils.lru import BoundedLRU


def get_storage_path(logical_path: str, rank: int, replicated: bool) -> str:
    """Reference ``io_preparer.py:51-57`` (``sharded/`` handled separately)."""
    return f"replicated/{logical_path}" if replicated else f"{rank}/{logical_path}"


def _is_jax_array(obj: Any) -> bool:
    import jax

    return isinstance(obj, jax.Array)


def _globally_replicated(arr: Any, world_size: int) -> bool:
    sharding = arr.sharding
    if not sharding.is_fully_replicated:
        return False
    procs = {d.process_index for d in sharding.device_set}
    return len(procs) == world_size and world_size > 1


def classify(value: Any, world_size: int) -> str:
    """One of: primitive | sharded | replicated_array | array | object."""
    if isinstance(value, PRIMITIVE_TYPES) and not isinstance(value, np.generic):
        return "primitive"
    if _is_jax_array(value):
        if _globally_replicated(value, world_size):
            return "replicated_array"
        procs = {d.process_index for d in value.sharding.device_set}
        if world_size > 1 and len(procs) == 1:
            # Device set confined to one process: this is per-rank data, not
            # a slice of a global array. The sharded path would write it to
            # rank-less ``sharded/<path>`` locations where different ranks'
            # distinct arrays at the same logical path clobber each other.
            return "array"
        if len(value.sharding.device_set) == 1:
            return "array"
        return "sharded"
    if isinstance(value, np.ndarray):
        return "array"
    return "object"


def _defensive_device_copies(arrs: List[Any]) -> List[Any]:
    """Fork jax arrays' device buffers for async capture — in ONE program.

    TPU-native replacement for the reference's defensive *host* copies
    (``io_preparers/tensor.py:254-278``): torch must capture mutable tensors
    in host RAM before ``async_take`` returns; jax arrays are immutable, so
    the only hazard is the training step *donating* the buffers
    (``donate_argnums``), which marks every reference deleted. An on-device
    copy (dispatched asynchronously — microseconds on the host timeline,
    HBM-bandwidth on the device) detaches the snapshot from donation.

    All leaves are copied in a single jitted call: per-leaf ``jit(jnp.copy)``
    would compile one XLA program per (sharding, shape) — tens of seconds of
    cold-start stall on a real transformer state — whereas one program
    compiles once per state *structure* and dispatches once per take.

    The copy runs under ``jit`` pinned to each array's own sharding: eager
    ``jnp.copy`` would raise on non-fully-addressable (multi-process) global
    arrays, and every rank reaches this point in the same gathered-key
    order, so the SPMD requirement holds. ``out_shardings`` is explicit —
    downstream routing (``classify``, shard enumeration) reads the copy's
    sharding, so propagation must not be allowed to pick a different one.

    One jitted computation requires all operands to share a device
    assignment, so leaves are grouped by assignment first (params on the
    full mesh vs. a step counter committed to one device vs. host-offloaded
    state); each group compiles and dispatches once.
    """
    groups: Dict[Any, List[int]] = {}
    for i, a in enumerate(arrs):
        groups.setdefault(_device_assignment_key(a.sharding), []).append(i)
    out: List[Any] = [None] * len(arrs)
    for indices in groups.values():
        group = [arrs[i] for i in indices]
        copies = _batch_copy_fn(tuple(a.sharding for a in group))(group)
        for i, c in zip(indices, copies):
            out[i] = c
    return out


def _device_assignment_key(sharding) -> Any:
    try:
        return tuple(d.id for d in sharding._device_assignment)
    except AttributeError:
        # Not part of jax's public API. Fall back to one group per distinct
        # sharding: equal shardings trivially share an assignment, while a
        # set-based key would merge same-device-set/different-order
        # assignments into one jit call, which jax rejects. Costs batching
        # granularity, never correctness.
        return sharding


def _batch_copy_fn(shardings: Tuple[Any, ...]):
    def build():
        import jax
        import jax.numpy as jnp

        return jax.jit(
            lambda xs: [jnp.copy(x) for x in xs], out_shardings=list(shardings)
        )

    return _BATCH_COPIES.get_or_build(shardings, build)


_BATCH_COPIES = BoundedLRU()


def prepare_write(
    flattened: Dict[str, Any],
    rank: int,
    world_size: int,
    replicated_paths: Set[str],
    is_async_snapshot: bool = False,
) -> Tuple[Manifest, List[WriteReq]]:
    """Plan all writes for this rank's flattened state (no data moves yet)."""
    manifest: Manifest = {}
    write_reqs: List[WriteReq] = []
    if is_async_snapshot:
        # Device arrays are immutable; fork them against donation and defer
        # their staging past async_take's return. Mutable host state keeps
        # defer_staging=False and is captured (staged under the budget)
        # before async_take returns — the reference's semantics
        # (``scheduler.py:178-214``).
        from .utils import knobs

        device_paths = [p for p, v in flattened.items() if _is_jax_array(v)]
        if device_paths and knobs.is_async_device_copy_enabled():
            copies = _defensive_device_copies([flattened[p] for p in device_paths])
            flattened = dict(flattened)
            flattened.update(zip(device_paths, copies))
    device_paths_set = {p for p, v in flattened.items() if _is_jax_array(v)}
    for logical_path, value in flattened.items():
        is_device_value = logical_path in device_paths_set
        kind = classify(value, world_size)
        glob_replicated = logical_path in replicated_paths

        if kind == "primitive":
            manifest[logical_path] = PrimitiveEntry.from_value(
                value, replicated=glob_replicated
            )
            continue

        if kind == "sharded":
            entry, reqs = ShardedArrayIOPreparer.prepare_write(
                logical_path, value, is_async_snapshot=is_async_snapshot
            )
            manifest[logical_path] = entry
            if is_async_snapshot:
                for r in reqs:
                    r.defer_staging = True
            write_reqs.extend(reqs)
            continue

        if kind in ("replicated_array", "array"):
            replicated = kind == "replicated_array" or glob_replicated
            arr = value
            if (
                _is_jax_array(arr)
                and len(arr.sharding.device_set) > 1
                and arr.sharding.is_fully_replicated
            ):
                # Fully-replicated multi-device array: stage from the local copy.
                arr = arr.addressable_shards[0].data
            storage_path = get_storage_path(logical_path, rank, replicated)
            if should_chunk(arr):
                entry, reqs = ChunkedArrayIOPreparer.prepare_write(
                    storage_path, arr, replicated, is_async_snapshot
                )
            else:
                entry, reqs = ArrayIOPreparer.prepare_write(
                    storage_path, arr, replicated, is_async_snapshot
                )
            manifest[logical_path] = entry
            if is_async_snapshot and is_device_value:
                for r in reqs:
                    r.defer_staging = True
            write_reqs.extend(reqs)
            continue

        # object fallback
        storage_path = get_storage_path(logical_path, rank, glob_replicated)
        entry, reqs = ObjectIOPreparer.prepare_write(
            storage_path, value, replicated=glob_replicated
        )
        manifest[logical_path] = entry
        write_reqs.extend(reqs)
    return manifest, write_reqs
