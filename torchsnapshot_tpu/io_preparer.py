"""Value -> (Entry, WriteReqs/ReadReqs) dispatch.

TPU-native analogue of the reference's ``io_preparer.py:51-178``, with the
routing redesigned around ``jax.Array``'s sharding metadata instead of
torch's type taxonomy:

- primitives -> inline :class:`PrimitiveEntry`;
- ``jax.Array`` **fully replicated across every process** -> the replicated
  array path (saved once globally, write load split by the partitioner).
  This replaces the reference's DDP-module sniffing
  (``snapshot.py:828-844``): on TPU, replication is *read off the sharding*,
  no user globs required;
- ``jax.Array`` on exactly one local device -> per-rank array path;
- any other ``jax.Array`` (sharded / partially replicated) -> the sharded
  path (elastic by construction);
- ``np.ndarray`` -> array path (replicated only via user glob);
- anything else -> pickled object.

Arrays whose serialized size exceeds the chunking knob are split into dim-0
chunks for transfer/I-O pipelining.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .io_types import WriteReq
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    Manifest,
    ObjectEntry,
    PrimitiveEntry,
    PRIMITIVE_TYPES,
)
from .io_preparers.array import ArrayIOPreparer
from .io_preparers.chunked_array import ChunkedArrayIOPreparer, should_chunk
from .io_preparers.object import ObjectIOPreparer
from .io_preparers.sharded_array import ShardedArrayIOPreparer


def get_storage_path(logical_path: str, rank: int, replicated: bool) -> str:
    """Reference ``io_preparer.py:51-57`` (``sharded/`` handled separately)."""
    return f"replicated/{logical_path}" if replicated else f"{rank}/{logical_path}"


def _is_jax_array(obj: Any) -> bool:
    import jax

    return isinstance(obj, jax.Array)


def _globally_replicated(arr: Any, world_size: int) -> bool:
    sharding = arr.sharding
    if not sharding.is_fully_replicated:
        return False
    procs = {d.process_index for d in sharding.device_set}
    return len(procs) == world_size and world_size > 1


def classify(value: Any, world_size: int) -> str:
    """One of: primitive | sharded | replicated_array | array | object."""
    if isinstance(value, PRIMITIVE_TYPES) and not isinstance(value, np.generic):
        return "primitive"
    if _is_jax_array(value):
        if _globally_replicated(value, world_size):
            return "replicated_array"
        procs = {d.process_index for d in value.sharding.device_set}
        if world_size > 1 and len(procs) == 1:
            # Device set confined to one process: this is per-rank data, not
            # a slice of a global array. The sharded path would write it to
            # rank-less ``sharded/<path>`` locations where different ranks'
            # distinct arrays at the same logical path clobber each other.
            return "array"
        if len(value.sharding.device_set) == 1:
            return "array"
        return "sharded"
    if isinstance(value, np.ndarray):
        return "array"
    return "object"


def _defensive_device_copy(arr: Any) -> Any:
    """Fork a jax array's device buffers for async capture.

    TPU-native replacement for the reference's defensive *host* copies
    (``io_preparers/tensor.py:254-278``): torch must capture mutable tensors
    in host RAM before ``async_take`` returns; jax arrays are immutable, so
    the only hazard is the training step *donating* the buffers
    (``donate_argnums``), which marks every reference deleted. An on-device
    copy (dispatched asynchronously — microseconds on the host timeline,
    HBM-bandwidth on the device) detaches the snapshot from donation.

    The copy runs under an explicit ``jit`` pinned to the array's own
    sharding: eager ``jnp.copy`` would raise on non-fully-addressable
    (multi-process) global arrays, and every rank reaches this point in the
    same gathered-key order, so the SPMD requirement holds.
    """
    from .utils import knobs

    if knobs.is_async_device_copy_enabled():
        arr = _jitted_copy(arr.sharding)(arr)
    return arr


def _jitted_copy(sharding):
    """Cache the jitted copy per sharding so repeat ``async_take`` calls hit
    jit's C++ fastpath instead of rebuilding a wrapper per leaf per call
    (O(leaf-count) Python dispatch on the stall-critical path otherwise)."""
    try:
        return _JITTED_COPIES[sharding]
    except KeyError:
        import jax
        import jax.numpy as jnp

        fn = jax.jit(jnp.copy, out_shardings=sharding)
        _JITTED_COPIES[sharding] = fn
        return fn


_JITTED_COPIES: Dict[Any, Any] = {}


def prepare_write(
    flattened: Dict[str, Any],
    rank: int,
    world_size: int,
    replicated_paths: Set[str],
    is_async_snapshot: bool = False,
) -> Tuple[Manifest, List[WriteReq]]:
    """Plan all writes for this rank's flattened state (no data moves yet)."""
    manifest: Manifest = {}
    write_reqs: List[WriteReq] = []
    for logical_path, value in flattened.items():
        is_device_value = _is_jax_array(value)
        if is_async_snapshot and is_device_value:
            # Device arrays are immutable; fork them against donation and
            # defer their staging past async_take's return. Mutable host
            # state keeps defer_staging=False and is captured (staged under
            # the budget) before async_take returns — the reference's
            # semantics (``scheduler.py:178-214``).
            value = _defensive_device_copy(value)
        kind = classify(value, world_size)
        glob_replicated = logical_path in replicated_paths

        if kind == "primitive":
            manifest[logical_path] = PrimitiveEntry.from_value(
                value, replicated=glob_replicated
            )
            continue

        if kind == "sharded":
            entry, reqs = ShardedArrayIOPreparer.prepare_write(
                logical_path, value, is_async_snapshot=is_async_snapshot
            )
            manifest[logical_path] = entry
            if is_async_snapshot:
                for r in reqs:
                    r.defer_staging = True
            write_reqs.extend(reqs)
            continue

        if kind in ("replicated_array", "array"):
            replicated = kind == "replicated_array" or glob_replicated
            arr = value
            if (
                _is_jax_array(arr)
                and len(arr.sharding.device_set) > 1
                and arr.sharding.is_fully_replicated
            ):
                # Fully-replicated multi-device array: stage from the local copy.
                arr = arr.addressable_shards[0].data
            storage_path = get_storage_path(logical_path, rank, replicated)
            if should_chunk(arr):
                entry, reqs = ChunkedArrayIOPreparer.prepare_write(
                    storage_path, arr, replicated, is_async_snapshot
                )
            else:
                entry, reqs = ArrayIOPreparer.prepare_write(
                    storage_path, arr, replicated, is_async_snapshot
                )
            manifest[logical_path] = entry
            if is_async_snapshot and is_device_value:
                for r in reqs:
                    r.defer_staging = True
            write_reqs.extend(reqs)
            continue

        # object fallback
        storage_path = get_storage_path(logical_path, rank, glob_replicated)
        entry, reqs = ObjectIOPreparer.prepare_write(
            storage_path, value, replicated=glob_replicated
        )
        manifest[logical_path] = entry
        write_reqs.extend(reqs)
    return manifest, write_reqs
