"""Host RNG capture (reference ``rng_state.py:13-38``).

JAX device randomness is explicit (``jax.random`` keys are ordinary arrays in
the app state, so they checkpoint like any other leaf). What still needs
capturing is *host* randomness used by data pipelines: Python's ``random`` and
NumPy's global generator. ``Snapshot`` treats ``RNGState`` specially to
guarantee the take/restore determinism invariant: the RNG state a restore
reinstates is the state as of the *beginning* of the take (see
``snapshot.py`` ``_pop_rng_state``; reference ``snapshot.py:341-376``).
"""

from __future__ import annotations

import random
from typing import Any, Dict

import numpy as np


class RNGState:
    def state_dict(self) -> Dict[str, Any]:
        return {
            "python": random.getstate(),
            "numpy": np.random.get_state(),
        }

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        random.setstate(state_dict["python"])
        np.random.set_state(state_dict["numpy"])
