"""Loader for the native I/O engine (``tss_io.cpp``).

The engine is a single C++ translation unit compiled on first use with the
host toolchain (``g++ -O2 -shared -fPIC``) and loaded via :mod:`ctypes` —
ctypes releases the GIL for the duration of each call, so bounce-buffer
copies and pwrite/pread syscalls overlap the asyncio event loop without a
C extension module.

Everything degrades gracefully: if no compiler is available, compilation
fails, or ``TORCHSNAPSHOT_TPU_DISABLE_NATIVE_IO=1`` is set, ``load_native()``
returns ``None`` and callers (the FS storage plugin) use the pure-Python
path. The built ``.so`` is cached next to the source (or in
``~/.cache/torchsnapshot_tpu`` when the package directory is read-only) and
rebuilt whenever the source is newer.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "tss_io.cpp")
_LIB_NAME = "libtss_io.so"

# _lock guards only the published (_lib, _load_attempted) state and is never
# held across a compile; _build_lock serializes the (multi-second) g++ build
# so nonblocking callers checking state don't queue behind it.
_lock = threading.Lock()
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_bg_build: Optional[threading.Thread] = None


def _candidate_lib_paths():
    yield os.path.join(os.path.dirname(__file__), _LIB_NAME)
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "torchsnapshot_tpu",
    )
    yield os.path.join(cache_dir, _LIB_NAME)


def _build(out_path: str) -> None:
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    # Build to a temp name then rename so concurrent processes never load a
    # half-written .so.
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(out_path), suffix=".so")
    os.close(fd)
    base = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp]
    try:
        try:
            subprocess.run(
                base + ["-lz"], check=True, capture_output=True, text=True
            )
        except subprocess.CalledProcessError:
            # No zlib dev files on this host: build the engine WITHOUT the
            # inline-crc digest API rather than losing O_DIRECT entirely
            # (Python hashing covers digests in that configuration).
            subprocess.run(
                base + ["-DTSS_NO_ZLIB"], check=True, capture_output=True, text=True
            )
        os.replace(tmp, out_path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.tss_io_version.restype = ctypes.c_int
    lib.tss_write_file.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_int,
        ctypes.c_uint64,
    ]
    lib.tss_write_file.restype = ctypes.c_int
    lib.tss_read_file.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_int,
        ctypes.c_uint64,
    ]
    lib.tss_read_file.restype = ctypes.c_int
    lib.tss_file_size.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.tss_file_size.restype = ctypes.c_int
    try:
        lib.tss_write_file_digest.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.tss_write_file_digest.restype = ctypes.c_int
        lib._tss_has_digest = True
    except AttributeError:  # pragma: no cover - stale cached .so
        lib._tss_has_digest = False
    try:
        lib.tss_write_at.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.c_int64,
        ]
        lib.tss_write_at.restype = ctypes.c_int
        lib._tss_has_write_at = True
    except AttributeError:  # pragma: no cover - stale cached .so
        lib._tss_has_write_at = False
    return lib


def _load_cached() -> Optional[ctypes.CDLL]:
    """dlopen an up-to-date cached ``.so`` if one exists (no build)."""
    for lib_path in _candidate_lib_paths():
        try:
            if os.path.exists(lib_path) and os.path.getmtime(
                lib_path
            ) >= os.path.getmtime(_SRC):
                lib = _configure(ctypes.CDLL(lib_path))
                logger.debug("Loaded native IO engine from %s", lib_path)
                return lib
        except OSError as e:
            logger.debug("Native IO engine unavailable at %s: %s", lib_path, e)
            continue
    return None


def _publish(lib: Optional[ctypes.CDLL]) -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _lock:
        if not _load_attempted:
            _lib = lib
            _load_attempted = True
        return _lib


def load_native() -> Optional[ctypes.CDLL]:
    """Return the native engine, building it if needed; None if unavailable."""
    from ..utils import knobs

    if not knobs.is_native_io_enabled():
        return None
    with _lock:
        if _load_attempted:
            return _lib
    lib = _load_cached()
    if lib is None:
        # Build under its own lock so _lock stays responsive for
        # load_native_nonblocking callers during the multi-second compile.
        with _build_lock:
            with _lock:
                if _load_attempted:
                    return _lib
            lib = _load_cached()  # another builder may have just finished
            if lib is None:
                for lib_path in _candidate_lib_paths():
                    try:
                        _build(lib_path)
                        lib = _configure(ctypes.CDLL(lib_path))
                        logger.debug("Built native IO engine at %s", lib_path)
                        break
                    except (OSError, subprocess.CalledProcessError) as e:
                        logger.debug(
                            "Native IO engine build failed at %s: %s", lib_path, e
                        )
                        continue
    if lib is None:
        logger.info("Native IO engine unavailable; using pure-Python file I/O")
    return _publish(lib)


def load_native_nonblocking() -> Optional[ctypes.CDLL]:
    """Like :func:`load_native`, but never blocks on compilation.

    If a current ``.so`` is cached on disk this loads it synchronously (a
    dlopen, milliseconds). Otherwise the g++ build runs on a daemon thread
    and this returns ``None`` until it completes — callers fall back to
    buffered I/O in the meantime, keeping first-``take`` latency free of the
    multi-second compile. ``_lock`` is never held across the build, so this
    never stalls behind an in-flight compile either.
    """
    global _bg_build
    from ..utils import knobs

    if not knobs.is_native_io_enabled():
        return None
    if _load_attempted:
        return _lib
    lib = _load_cached()
    if lib is not None:
        return _publish(lib)
    with _lock:
        if _load_attempted:
            return _lib
        if _bg_build is None or not _bg_build.is_alive():
            _bg_build = threading.Thread(
                target=load_native, daemon=True, name="tss-native-build"
            )
            _bg_build.start()
    return None


def _as_uint8_view(buf) -> "memoryview":
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.format not in ("B", "b", "c"):
        mv = mv.cast("B")
    return mv


def _buf_address(mv: memoryview) -> int:
    # numpy gives a stable pointer for read-only buffers, which
    # ctypes.from_buffer refuses.
    import numpy as np

    return np.frombuffer(mv, dtype=np.uint8).ctypes.data if mv.nbytes else 0


def write_file(lib: ctypes.CDLL, path: str, buf, *, direct: bool, chunk_bytes: int) -> None:
    """Write ``buf`` (any buffer-protocol object) to ``path`` via the engine."""
    mv = _as_uint8_view(buf)
    rc = lib.tss_write_file(
        os.fsencode(path), _buf_address(mv), mv.nbytes, 1 if direct else 0, chunk_bytes
    )
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), path)


def write_file_digest(
    lib: ctypes.CDLL,
    path: str,
    buf,
    *,
    direct: bool,
    chunk_bytes: int,
):
    """Write ``buf`` and return its ``[crc32, size, None]`` digest, the crc
    computed inside the write loop (no extra memory pass). The sha256 slot
    is None by design — hashlib's OpenSSL (SHA-NI) implementation beats any
    embedded portable one, so collision-resistant dedup digests stay in
    Python and the scheduler fills the slot when it needs one.

    Returns None when the loaded engine predates the digest API — the
    caller then writes via :func:`write_file` and hashes in Python.
    """
    if not getattr(lib, "_tss_has_digest", False):
        return None
    mv = _as_uint8_view(buf)
    crc = ctypes.c_uint32(0)
    rc = lib.tss_write_file_digest(
        os.fsencode(path),
        _buf_address(mv),
        mv.nbytes,
        1 if direct else 0,
        chunk_bytes,
        ctypes.byref(crc),
    )
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return [crc.value, mv.nbytes, None]


def supports_write_at(lib: ctypes.CDLL) -> bool:
    """Whether the loaded engine has the streamed positioned-write API (a
    stale cached ``.so`` built from older source may not)."""
    return bool(getattr(lib, "_tss_has_write_at", False))


def write_at(
    lib: ctypes.CDLL,
    path: str,
    buf,
    *,
    offset: int,
    direct: bool,
    chunk_bytes: int,
    truncate_to: int = -1,
) -> None:
    """Write ``buf`` at byte ``offset`` of ``path`` (created, not truncated,
    on open). O_DIRECT engages only for sector-aligned offset+length —
    streamed appends keep their unaligned tail in Python and flush it here
    buffered at commit, with ``truncate_to`` setting the final size."""
    mv = _as_uint8_view(buf)
    rc = lib.tss_write_at(
        os.fsencode(path),
        _buf_address(mv),
        mv.nbytes,
        offset,
        1 if direct else 0,
        chunk_bytes,
        truncate_to,
    )
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), path)


def read_into(
    lib: ctypes.CDLL,
    path: str,
    dst,
    *,
    offset: int = 0,
    direct: bool = True,
    chunk_bytes: int = 64 << 20,
) -> None:
    """Fill writable buffer ``dst`` from ``path[offset : offset+len(dst)]``."""
    mv = _as_uint8_view(dst)
    if mv.readonly:
        raise ValueError("read_into requires a writable buffer")
    rc = lib.tss_read_file(
        os.fsencode(path),
        _buf_address(mv),
        offset,
        mv.nbytes,
        1 if direct else 0,
        chunk_bytes,
    )
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), path)


def file_size(lib: ctypes.CDLL, path: str) -> int:
    out = ctypes.c_uint64(0)
    rc = lib.tss_file_size(os.fsencode(path), ctypes.byref(out))
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return out.value
