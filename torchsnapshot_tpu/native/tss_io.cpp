// Native I/O engine: O_DIRECT file read/write with buffered fallback.
//
// Rationale (TPU-VM analogue of the reference's performance layer): the
// reference (pure Python) relies on the OS page cache for write throughput
// (torchsnapshot/storage_plugins/fs.py:19-54 via aiofiles). On TPU-VM hosts
// buffered writeback is typically throttled far below device bandwidth
// (measured here: ~0.12 GB/s buffered vs ~0.62 GB/s O_DIRECT writes and
// ~0.57 GB/s vs ~2.0 GB/s cold reads), so checkpoint streaming goes through
// this engine instead: aligned O_DIRECT transfers with an internal bounce
// buffer, falling back to buffered I/O wherever O_DIRECT is unsupported
// (tmpfs, overlayfs, unaligned tails).
//
// C ABI only — loaded from Python via ctypes (which releases the GIL for the
// duration of each call, so copies and syscalls overlap the event loop).
//
// All functions return 0 on success or -errno on failure.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

// The inline-crc digest path needs zlib headers; hosts without zlib dev
// files build with -DTSS_NO_ZLIB (the loader retries with it) and keep the
// full IO engine, just without tss_write_file_digest — Python hashing
// covers digests there.
#ifndef TSS_NO_ZLIB
#include <zlib.h>
#endif

namespace {

constexpr uint64_t kAlign = 4096;  // covers 512/4096 logical sector sizes

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) / kAlign * kAlign; }
uint64_t align_down(uint64_t v) { return v / kAlign * kAlign; }

#ifndef TSS_NO_ZLIB
// Running CRC32 updated as write chunks advance (bytes hashed exactly once,
// in file order, while the chunk is cache-hot from the bounce copy).
// Deliberately crc-only: an embedded scalar SHA-256 was tried and measured
// ~5-10x slower than Python hashlib's OpenSSL (SHA-NI) path, so
// collision-resistant dedup digests stay in Python where the hardware
// implementation lives.
struct HashCtx {
  uLong crc = crc32(0L, Z_NULL, 0);

  void update(const char* p, uint64_t n) {
    const Bytef* b = reinterpret_cast<const Bytef*>(p);
    uint64_t done = 0;
    while (done < n) {  // zlib's crc32 takes uInt lengths
      uInt step = static_cast<uInt>(std::min<uint64_t>(n - done, 1u << 30));
      crc = crc32(crc, b + done, step);
      done += step;
    }
  }
};
#else
struct HashCtx {  // digest API absent; keeps write_impl's signature uniform
  void update(const char*, uint64_t) {}
};
#endif

// Buffered positional write of [src, src+nbytes) at file offset `off`.
int write_buffered(int fd, const char* src, uint64_t nbytes, uint64_t off,
                   HashCtx* hc = nullptr) {
  uint64_t done = 0;
  while (done < nbytes) {
    size_t n = std::min<uint64_t>(nbytes - done, 1ull << 30);
    ssize_t w = pwrite(fd, src + done, n, off + done);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (hc) hc->update(src + done, static_cast<uint64_t>(w));
    done += static_cast<uint64_t>(w);
  }
  return 0;
}

int read_buffered(int fd, char* dst, uint64_t nbytes, uint64_t off) {
  uint64_t done = 0;
  while (done < nbytes) {
    size_t n = std::min<uint64_t>(nbytes - done, 1ull << 30);
    ssize_t r = pread(fd, dst + done, n, off + done);
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return -EIO;  // unexpected EOF: caller sized the read
    done += static_cast<uint64_t>(r);
  }
  return 0;
}

// Shared implementation of the write entry points; `hc` (nullable) receives
// a running crc32 over the bytes, updated chunk-by-chunk while the data is
// cache-hot from the bounce-buffer copy.
int write_impl(const char* path, const void* buf, uint64_t nbytes,
               int use_direct, uint64_t chunk_bytes, HashCtx* hc) {
  const char* src = static_cast<const char*>(buf);
  const int base_flags = O_WRONLY | O_CREAT | O_TRUNC;

  int fd = -1;
  bool direct = use_direct != 0 && nbytes >= kAlign;
  if (direct) {
    fd = open(path, base_flags | O_DIRECT, 0644);
    if (fd < 0) direct = false;  // fs without O_DIRECT support
  }
  if (fd < 0) fd = open(path, base_flags, 0644);
  if (fd < 0) return -errno;

  int rc = 0;
  uint64_t off = 0;
  if (direct) {
    if (chunk_bytes < kAlign) chunk_bytes = 64ull << 20;
    chunk_bytes = align_down(chunk_bytes);
    void* bounce = nullptr;
    if (posix_memalign(&bounce, kAlign, chunk_bytes) != 0) {
      close(fd);
      return -ENOMEM;
    }
    while (off < nbytes) {
      uint64_t n = std::min(chunk_bytes, nbytes - off);
      uint64_t padded = align_up(n);
      memcpy(bounce, src + off, n);
      if (padded > n) memset(static_cast<char*>(bounce) + n, 0, padded - n);
      ssize_t w = pwrite(fd, bounce, padded, off);
      if (w < 0) {
        if (errno == EINTR) continue;
        if (errno == EINVAL) break;  // device rejected O_DIRECT mid-stream
        rc = -errno;
        break;
      }
      // A short direct write only advances at an aligned boundary; a
      // sub-sector (or zero) count means this fs can't make progress under
      // O_DIRECT — finish buffered below rather than spinning.
      uint64_t advanced = std::min<uint64_t>(align_down(static_cast<uint64_t>(w)), n);
      if (advanced == 0) break;
      if (hc) hc->update(src + off, advanced);
      off += advanced;
    }
    free(bounce);
    if (rc == 0 && off < nbytes) {
      // Finish buffered (EINVAL fallback or zero-length write).
      int fd2 = open(path, O_WRONLY, 0644);
      if (fd2 < 0) {
        rc = -errno;
      } else {
        rc = write_buffered(fd2, src + off, nbytes - off, off, hc);
        if (close(fd2) < 0 && rc == 0) rc = -errno;
      }
    }
    // Drop the alignment padding from the final chunk.
    if (rc == 0 && ftruncate(fd, static_cast<off_t>(nbytes)) < 0) rc = -errno;
  } else {
    rc = write_buffered(fd, src, nbytes, 0, hc);
  }
  if (close(fd) < 0 && rc == 0) rc = -errno;
  return rc;
}

}  // namespace

extern "C" {

int tss_io_version() { return 3; }

// Create/truncate `path` and write `nbytes` from `buf`.
// use_direct != 0 attempts O_DIRECT via an aligned bounce buffer of
// chunk_bytes; any O_DIRECT failure falls back to buffered I/O and the write
// still succeeds.
int tss_write_file(const char* path, const void* buf, uint64_t nbytes,
                   int use_direct, uint64_t chunk_bytes) {
  return write_impl(path, buf, nbytes, use_direct, chunk_bytes, nullptr);
}

#ifndef TSS_NO_ZLIB
// Like tss_write_file, but also computes the zlib crc32 over the written
// bytes in the same pass (*crc_out): the separate memory sweep the Python
// hashing path pays per object is folded into the write loop here.
int tss_write_file_digest(const char* path, const void* buf, uint64_t nbytes,
                          int use_direct, uint64_t chunk_bytes,
                          uint32_t* crc_out) {
  HashCtx hc;
  int rc = write_impl(path, buf, nbytes, use_direct, chunk_bytes, &hc);
  if (rc == 0 && crc_out) *crc_out = static_cast<uint32_t>(hc.crc);
  return rc;
}
#endif

// Positioned write for STREAMED objects: write `nbytes` from `buf` at byte
// `offset` of `path` (created if absent, never truncated on open — earlier
// appends stay). use_direct engages O_DIRECT only when `offset` and `nbytes`
// are both sector-aligned (the streaming caller keeps an unaligned tail in
// Python and flushes it buffered at commit); any O_DIRECT failure falls back
// to buffered I/O. `truncate_to` >= 0 ftruncates the file to that size after
// the write (the commit call drops O_DIRECT padding / sets the final size).
int tss_write_at(const char* path, const void* buf, uint64_t nbytes,
                 uint64_t offset, int use_direct, uint64_t chunk_bytes,
                 int64_t truncate_to) {
  const char* src = static_cast<const char*>(buf);
  const int base_flags = O_WRONLY | O_CREAT;

  int fd = -1;
  bool direct = use_direct != 0 && nbytes >= kAlign &&
                offset == align_down(offset) && nbytes == align_down(nbytes);
  if (direct) {
    fd = open(path, base_flags | O_DIRECT, 0644);
    if (fd < 0) direct = false;  // fs without O_DIRECT support
  }
  if (fd < 0) fd = open(path, base_flags, 0644);
  if (fd < 0) return -errno;

  int rc = 0;
  uint64_t done = 0;
  if (direct) {
    if (chunk_bytes < kAlign) chunk_bytes = 64ull << 20;
    chunk_bytes = align_down(chunk_bytes);
    void* bounce = nullptr;
    if (posix_memalign(&bounce, kAlign, chunk_bytes) != 0) {
      close(fd);
      return -ENOMEM;
    }
    while (done < nbytes) {
      uint64_t n = std::min(chunk_bytes, nbytes - done);  // aligned: so is n
      memcpy(bounce, src + done, n);
      ssize_t w = pwrite(fd, bounce, n, offset + done);
      if (w < 0) {
        if (errno == EINTR) continue;
        if (errno == EINVAL) break;  // device rejected O_DIRECT mid-stream
        rc = -errno;
        break;
      }
      uint64_t advanced = align_down(static_cast<uint64_t>(w));
      if (advanced == 0) break;  // no O_DIRECT progress: finish buffered
      done += advanced;
    }
    free(bounce);
    if (rc == 0 && done < nbytes) {
      int fd2 = open(path, O_WRONLY, 0644);
      if (fd2 < 0) {
        rc = -errno;
      } else {
        rc = write_buffered(fd2, src + done, nbytes - done, offset + done);
        if (close(fd2) < 0 && rc == 0) rc = -errno;
      }
    }
  } else {
    rc = write_buffered(fd, src, nbytes, offset);
  }
  if (rc == 0 && truncate_to >= 0 &&
      ftruncate(fd, static_cast<off_t>(truncate_to)) < 0) {
    rc = -errno;
  }
  if (close(fd) < 0 && rc == 0) rc = -errno;
  return rc;
}

// Read `nbytes` at byte `offset` of `path` into `dst`. Fails with -EIO if the
// file is shorter than offset+nbytes (callers size reads from the manifest).
int tss_read_file(const char* path, void* dst, uint64_t offset, uint64_t nbytes,
                  int use_direct, uint64_t chunk_bytes) {
  char* out = static_cast<char*>(dst);

  int fd = -1;
  bool direct = use_direct != 0 && nbytes >= kAlign;
  if (direct) {
    fd = open(path, O_RDONLY | O_DIRECT);
    if (fd < 0) direct = false;
  }
  if (fd < 0) fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;

  int rc = 0;
  if (direct) {
    if (chunk_bytes < kAlign) chunk_bytes = 64ull << 20;
    chunk_bytes = align_down(chunk_bytes);
    void* bounce = nullptr;
    if (posix_memalign(&bounce, kAlign, chunk_bytes) != 0) {
      close(fd);
      return -ENOMEM;
    }
    struct stat st;
    if (fstat(fd, &st) < 0) {
      free(bounce);
      close(fd);
      return -errno;
    }
    const uint64_t file_size = static_cast<uint64_t>(st.st_size);
    if (offset + nbytes > file_size) {
      free(bounce);
      close(fd);
      return -EIO;
    }
    uint64_t done = 0;
    while (done < nbytes && rc == 0) {
      const uint64_t want_off = offset + done;          // unaligned file offset
      const uint64_t read_off = align_down(want_off);   // aligned read start
      const uint64_t lead = want_off - read_off;
      uint64_t n = std::min(chunk_bytes - lead, nbytes - done);
      // O_DIRECT reads must not extend past EOF by more than a sector pad.
      uint64_t padded = std::min(align_up(lead + n), align_up(file_size - read_off));
      ssize_t r = pread(fd, bounce, padded, read_off);
      if (r < 0) {
        if (errno == EINTR) continue;
        if (errno == EINVAL) break;  // fall back below
        rc = -errno;
        break;
      }
      uint64_t got = static_cast<uint64_t>(r);
      if (got <= lead) {
        // No forward progress under O_DIRECT (short read at an unaligned
        // boundary — seen on NFS/FUSE). Mirror the write path: finish via
        // the buffered fallback below instead of failing the restore.
        break;
      }
      uint64_t usable = std::min(got - lead, n);
      memcpy(out + done, static_cast<char*>(bounce) + lead, usable);
      done += usable;
    }
    free(bounce);
    if (rc == 0 && done < nbytes) {
      int fd2 = open(path, O_RDONLY);
      if (fd2 < 0) {
        rc = -errno;
      } else {
        rc = read_buffered(fd2, out + done, nbytes - done, offset + done);
        close(fd2);
      }
    }
  } else {
    rc = read_buffered(fd, out, nbytes, offset);
  }
  if (close(fd) < 0 && rc == 0) rc = -errno;
  return rc;
}

// File size probe (0 on success with *size set).
int tss_file_size(const char* path, uint64_t* size) {
  struct stat st;
  if (stat(path, &st) < 0) return -errno;
  *size = static_cast<uint64_t>(st.st_size);
  return 0;
}

}  // extern "C"
