"""URL -> StoragePlugin dispatch (reference ``storage_plugin.py:17-68``).

Builtin protocols: ``fs://`` (and bare paths), ``memory://``, ``gs://``,
``s3://``. Third-party plugins register via the ``torchsnapshot_tpu.storage_plugins``
entry-point group, mirroring the reference's ``storage_plugins`` group.

Also home of the telemetry-artifact write path
(:func:`write_telemetry_artifact`): artifacts persist through the
snapshot's own plugin — fs/gs/s3/memory alike — and the write is fail-open
by contract (diagnostics must never fail or delay a checkpoint commit).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from . import telemetry
from .io_types import StoragePlugin, WriteIO

logger = logging.getLogger(__name__)

# Artifact persistence failures log loudly ONCE per process (with the
# traceback) and quietly thereafter: a wedged diagnostics path must not spam
# a warning per rank-file per checkpoint interval.
_artifact_write_warned = False


def write_telemetry_artifact(
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
    path: str,
    payload: bytes,
) -> bool:
    """Fail-open write of one telemetry artifact through ``storage``.

    Returns True when the artifact landed. Any failure — plugin error,
    read-only backend, closed loop — is logged (once per process with the
    traceback, then at debug) and swallowed: telemetry persistence must
    never fail or delay the snapshot commit it rides alongside.
    """
    global _artifact_write_warned
    try:
        with telemetry.span(
            "telemetry.artifact_write",
            cat="telemetry",
            path=path,
            nbytes=len(payload),
        ):
            storage.sync_write(WriteIO(path=path, buf=payload), event_loop)
        return True
    except Exception:  # noqa: BLE001 - fail-open by contract
        if not _artifact_write_warned:
            _artifact_write_warned = True
            logger.warning(
                "failed to persist telemetry artifact %s (snapshot commit "
                "unaffected; further artifact-write failures log at DEBUG)",
                path,
                exc_info=True,
            )
        else:
            logger.debug(
                "failed to persist telemetry artifact %s", path, exc_info=True
            )
        return False


def url_to_storage_plugin(url_path: str) -> StoragePlugin:
    plugin = _resolve_storage_plugin(url_path)
    from .utils import knobs

    if knobs.is_debug_effects_enabled():
        # Durable-effect journal (debug/tests only): the BOTTOM of the
        # wrapper stack, directly above the real backend, so it records
        # exactly the mutations that reached storage — including a torn
        # write's partial append, excluding ops a fault rule suppressed.
        # See effect_journal.py / dev/crash_explorer.py.
        from .effect_journal import maybe_wrap_with_effects

        plugin = maybe_wrap_with_effects(plugin, origin=url_path)
    if knobs.get_read_cache_dir():
        # Content-addressed read-through cache (serving fleets: K replicas
        # cold-start from one snapshot, the origin is read once). Wrapped
        # BELOW the fault injector so chaos schedules exercise the cache
        # surface too. See storage_plugins/cache.py.
        from .storage_plugins.cache import maybe_wrap_with_read_cache

        plugin = maybe_wrap_with_read_cache(plugin, origin_id=url_path)
    if knobs.get_faults_spec():
        # Deterministic fault injection (tests only): wrap EVERY plugin this
        # process — and, since the env var is inherited, every child rank —
        # constructs, so a single seeded spec drives faults across a whole
        # fake pod. See faults.py / docs/robustness.md.
        from .faults import maybe_wrap_with_faults

        plugin = maybe_wrap_with_faults(plugin)
    return plugin


def _resolve_storage_plugin(url_path: str) -> StoragePlugin:
    if "://" in url_path:
        protocol, _, path = url_path.partition("://")
        if protocol == "":
            raise RuntimeError(f"Malformed URL: {url_path}")
    else:
        protocol, path = "fs", url_path

    if protocol == "fs":
        from .storage_plugins.fs import FSStoragePlugin

        return FSStoragePlugin(root=path)
    if protocol == "memory":
        from .storage_plugins.memory import MemoryStoragePlugin, _SHARED_ROOTS

        return _SHARED_ROOTS.setdefault(path, MemoryStoragePlugin(root=path))
    if protocol == "gs":
        from .storage_plugins.gcs import GCSStoragePlugin

        return GCSStoragePlugin(root=path)
    if protocol == "s3":
        from .storage_plugins.s3 import S3StoragePlugin

        return S3StoragePlugin(root=path)

    # Entry-point-registered third-party plugins.
    try:
        from importlib.metadata import entry_points

        eps = entry_points(group="torchsnapshot_tpu.storage_plugins")
        for ep in eps:
            if ep.name == protocol:
                return ep.load()(path)
    except Exception:
        pass
    raise RuntimeError(f"Unsupported protocol: {protocol} (in url {url_path})")


def url_to_storage_plugin_in_event_loop(
    url_path: str, event_loop: Optional[asyncio.AbstractEventLoop] = None
) -> StoragePlugin:
    # Plugin construction may need the loop (e.g. client session creation).
    return url_to_storage_plugin(url_path)
