"""URL -> StoragePlugin dispatch (reference ``storage_plugin.py:17-68``).

Builtin protocols: ``fs://`` (and bare paths), ``memory://``, ``gs://``,
``s3://``. Third-party plugins register via the ``torchsnapshot_tpu.storage_plugins``
entry-point group, mirroring the reference's ``storage_plugins`` group.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from .io_types import StoragePlugin


def url_to_storage_plugin(url_path: str) -> StoragePlugin:
    if "://" in url_path:
        protocol, _, path = url_path.partition("://")
        if protocol == "":
            raise RuntimeError(f"Malformed URL: {url_path}")
    else:
        protocol, path = "fs", url_path

    if protocol == "fs":
        from .storage_plugins.fs import FSStoragePlugin

        return FSStoragePlugin(root=path)
    if protocol == "memory":
        from .storage_plugins.memory import MemoryStoragePlugin, _SHARED_ROOTS

        return _SHARED_ROOTS.setdefault(path, MemoryStoragePlugin(root=path))
    if protocol == "gs":
        from .storage_plugins.gcs import GCSStoragePlugin

        return GCSStoragePlugin(root=path)
    if protocol == "s3":
        from .storage_plugins.s3 import S3StoragePlugin

        return S3StoragePlugin(root=path)

    # Entry-point-registered third-party plugins.
    try:
        from importlib.metadata import entry_points

        eps = entry_points(group="torchsnapshot_tpu.storage_plugins")
        for ep in eps:
            if ep.name == protocol:
                return ep.load()(path)
    except Exception:
        pass
    raise RuntimeError(f"Unsupported protocol: {protocol} (in url {url_path})")


def url_to_storage_plugin_in_event_loop(
    url_path: str, event_loop: Optional[asyncio.AbstractEventLoop] = None
) -> StoragePlugin:
    # Plugin construction may need the loop (e.g. client session creation).
    return url_to_storage_plugin(url_path)
