"""Debug-mode collective lockstep sanitizer (``TORCHSNAPSHOT_TPU_DEBUG_COLLECTIVES``).

The runtime half of the collective-discipline story: the static TSA9xx pass
(``dev/analyze/collective_discipline.py``) proves over the control-flow
graph that no collective is reachable from rank-divergent state, and this
tracer proves the same invariant over *actual executions* — the two
cross-check each other in CI (the chaos matrix and the multiprocess suites
run with the knob on).

When the knob is set, every coordinator collective (``barrier``,
``all_gather_object``, ``broadcast_object``, ``gather_object``,
``scatter_object``) and every :class:`~.parallel.store.LinearBarrier` phase
is journaled with:

- a **monotonic sequence number** (per process),
- the **op kind** and its **key fingerprint** (the collective's generation
  namespace / the barrier id + phase — SPMD-invariant by construction, never
  payload contents, which legitimately differ per rank),
- the **originating call site** — the first stack frame below the
  coordinator/store/tracer plumbing.

Each journaled lockstep op folds into a rolling sha256 fingerprint. At every
barrier (coordinator barrier, and LinearBarrier arrive/depart on the main
thread) the tracer cross-checks ``(sequence count, rolling fingerprint)``
against every peer through the coordinator store; a mismatch exchanges the
journals and raises :class:`CollectiveDivergenceError` on EVERY rank, naming
rank A @ site X vs rank B @ site Y and the **first divergent sequence
number** — turning "the fleet deadlocked / a broadcast delivered the wrong
generation's bytes" into a one-line attribution at the barrier where
lockstep broke.

Ops that are *deliberately* asymmetric — ``defer_delete`` (only the posting
rank registers its own key for GC), ``report_error`` (only the failing rank
posts), and any collective issued off the main thread (the async-commit
background barrier: its interleaving against main-thread planning is
timing-dependent, not SPMD-divergent) — are journaled for attribution but
excluded from the checked fingerprint.

Production jobs leave the knob unset: no tracer object is ever allocated and
the collective paths pay one environment lookup per call.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import traceback
from typing import List, Optional, Tuple

__all__ = [
    "CollectiveTracer",
    "CollectiveDivergenceError",
    "active_tracer",
    "reset_tracer",
]

# Journal retention cap: the digest keeps rolling forever, but only the most
# recent entries are retained for divergence attribution (a divergence older
# than the window is still *detected*, just attributed approximately).
_MAX_JOURNAL = 65536


class CollectiveDivergenceError(RuntimeError):
    """Two ranks issued different collective sequences. Carries the first
    divergent sequence number and both ranks' call sites."""

    def __init__(
        self,
        message: str,
        seq: Optional[int] = None,
        rank_a: Optional[int] = None,
        site_a: Optional[str] = None,
        rank_b: Optional[int] = None,
        site_b: Optional[str] = None,
    ) -> None:
        super().__init__(message)
        self.seq = seq
        self.rank_a = rank_a
        self.site_a = site_a
        self.rank_b = rank_b
        self.site_b = site_b


_PLUMBING_FILES = ("collective_tracer.py", "coordinator.py", "store.py")


def _origin_site() -> str:
    """file:line(function) of the frame that issued the collective — the
    first frame below the tracer/coordinator/store plumbing."""
    for frame in reversed(traceback.extract_stack()):
        if os.path.basename(frame.filename) in _PLUMBING_FILES:
            continue
        filename = frame.filename
        marker = "torchsnapshot_tpu"
        idx = filename.rfind(marker)
        if idx != -1:
            filename = filename[idx:]
        else:
            filename = filename.rsplit("/", 1)[-1]
        return f"{filename}:{frame.lineno} ({frame.name})"
    return "<unknown>"


class CollectiveTracer:
    """Thread-safe lockstep journal + store-backed cross-check.

    ``record`` appends ``(seq, op, key, site)`` entries; lockstep ops
    (``checked=True`` and issued from the main thread) additionally fold
    ``op`` and ``key`` into the rolling fingerprint that :meth:`crosscheck`
    compares across ranks. Journal entries are retained up to a cap for
    attribution; the fingerprint itself never truncates.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seq = 0  # checked (lockstep) sequence counter
        self._fp = b""  # rolling fingerprint over checked ops
        # Retained checked entries: (seq, op, key, site).
        self._journal: List[Tuple[int, str, str, str]] = []
        self._dropped = 0
        # Unchecked (asymmetric-by-design / off-main-thread) entries keep
        # their own annotation so a divergence report can still show them.
        self._unchecked: List[Tuple[int, str, str, str]] = []
        # Own digest keys posted by PREVIOUS successful crosschecks, safe to
        # delete once every rank passed them (i.e. at the next crosscheck).
        self._gc: List = []

    # ------------------------------------------------------------- recording
    def record(self, op: str, key: str = "", checked: bool = True) -> int:
        """Journal one collective; returns its sequence number. Lockstep ops
        must be recorded BEFORE the op blocks, so a peer diagnosing a hang
        sees the in-flight op at the tail of this rank's journal."""
        site = _origin_site()
        on_main = threading.current_thread() is threading.main_thread()
        with self._lock:
            if not (checked and on_main):
                self._unchecked.append((self._seq, op, key, site))
                if len(self._unchecked) > _MAX_JOURNAL:
                    del self._unchecked[: len(self._unchecked) // 2]
                return self._seq
            self._seq += 1
            self._fp = hashlib.sha256(
                self._fp + op.encode() + b"\0" + key.encode()
            ).digest()
            self._journal.append((self._seq, op, key, site))
            if len(self._journal) > _MAX_JOURNAL:
                drop = len(self._journal) // 2
                self._dropped += drop
                del self._journal[:drop]
            return self._seq

    # ------------------------------------------------------------ inspection
    def digest(self) -> Tuple[int, str]:
        """(checked sequence count, rolling fingerprint hex)."""
        with self._lock:
            return self._seq, self._fp.hex()

    def checked_entries(self) -> List[Tuple[int, str, str, str]]:
        with self._lock:
            return list(self._journal)

    def unchecked_entries(self) -> List[Tuple[int, str, str, str]]:
        with self._lock:
            return list(self._unchecked)

    # ------------------------------------------------------------ crosscheck
    def crosscheck(
        self,
        store,
        rank: int,
        world_size: int,
        tag: str,
        timeout_s: float = 60.0,
    ) -> None:
        """Compare this rank's (seq, fingerprint) against every peer.

        Called at the same program point on every rank (a barrier every rank
        just passed), with an identical ``tag`` — tags must be derived from
        the barrier's identity (generation counter / barrier id + phase),
        never from local state, so divergent ranks still rendezvous here.
        Raises :class:`CollectiveDivergenceError` on mismatch (on every
        rank), after exchanging journals for first-divergence attribution.
        """
        if world_size <= 1:
            return
        ns = store.prefix(f"colltrace/{tag}")
        # Keys from previous rounds: every rank passed those crosschecks, so
        # own postings are safe to reclaim now.
        with self._lock:
            gc, self._gc = self._gc, []
        for old_ns, old_key in gc:
            try:
                old_ns.delete(old_key)
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                pass
        mine = self.digest()
        ns.set(str(rank), pickle.dumps(mine, protocol=pickle.HIGHEST_PROTOCOL))
        peers = {}
        for r in range(world_size):
            if r == rank:
                peers[r] = mine
            else:
                peers[r] = pickle.loads(ns.get(str(r), timeout_s=timeout_s))
        mismatched = sorted(r for r, d in peers.items() if d != mine)
        if not mismatched:
            with self._lock:
                self._gc.append((ns, str(rank)))
            return
        # Divergence: every rank observes the same digest set, so every rank
        # posts its journal and reads the lowest mismatching peer's.
        ns.set(
            f"journal/{rank}",
            pickle.dumps(
                (self._dropped, self.checked_entries()),
                protocol=pickle.HIGHEST_PROTOCOL,
            ),
        )
        other = mismatched[0]
        other_dropped, other_journal = pickle.loads(
            ns.get(f"journal/{other}", timeout_s=timeout_s)
        )
        raise self._divergence(rank, other, other_dropped, other_journal, tag)

    def _divergence(
        self,
        rank: int,
        other: int,
        other_dropped: int,
        other_journal: List[Tuple[int, str, str, str]],
        tag: str,
    ) -> CollectiveDivergenceError:
        mine = {seq: (op, key, site) for seq, op, key, site in self.checked_entries()}
        theirs = {seq: (op, key, site) for seq, op, key, site in other_journal}
        first = None
        for seq in sorted(set(mine) | set(theirs)):
            a, b = mine.get(seq), theirs.get(seq)
            if a is None or b is None or a[:2] != b[:2]:
                first = seq
                break
        if first is None:
            # Same retained entries yet different digests: the divergence
            # predates both retained windows.
            window = max(self._dropped, other_dropped)
            return CollectiveDivergenceError(
                f"collective lockstep divergence at {tag}: ranks {rank} and "
                f"{other} disagree before the retained journal window "
                f"(seq <= {window})",
                rank_a=rank,
                rank_b=other,
            )

        def describe(entry, who: int) -> str:
            if entry is None:
                return f"rank {who}: <no collective at this sequence number>"
            op, key, site = entry
            return f"rank {who}: {op}({key}) at {site}"

        a, b = mine.get(first), theirs.get(first)
        return CollectiveDivergenceError(
            f"collective lockstep divergence at {tag}, first divergent "
            f"sequence number {first}:\n"
            f"  {describe(a, rank)}\n"
            f"  {describe(b, other)}\n"
            "every collective must be issued identically on every rank "
            "(see docs/robustness.md, lockstep sanitizer)",
            seq=first,
            rank_a=rank,
            site_a=a[2] if a else None,
            rank_b=other,
            site_b=b[2] if b else None,
        )


# One tracer per process (collective lockstep is a per-process property,
# like the coordinator itself). Created lazily on first use with the knob
# set; the knob is re-read per call so test overrides take effect, but the
# off path allocates nothing.
_TRACER: Optional[CollectiveTracer] = None


def active_tracer() -> Optional[CollectiveTracer]:
    """The process tracer when ``TORCHSNAPSHOT_TPU_DEBUG_COLLECTIVES`` is
    set, else None (the production path pays one env lookup, no allocation)."""
    global _TRACER
    from .utils import knobs

    if not knobs.is_debug_collectives_enabled():
        return None
    if _TRACER is None:
        _TRACER = CollectiveTracer()
    return _TRACER


def reset_tracer() -> None:
    """Drop the process tracer (tests; a fresh journal per scenario)."""
    global _TRACER
    _TRACER = None
