"""Array write/read preparation: the D2H + serialization hot path.

TPU-native analogue of the reference's ``io_preparers/tensor.py:45-376``. The
reference's performance trick is overlapping CUDA D2H copies (run on a
GIL-dropping jit-scripted helper inside a thread pool) with storage I/O; the
XLA-native equivalent used here is:

1. ``jax.Array.copy_to_host_async()`` at the start of staging — enqueues the
   transfer on the device without blocking the Python thread or the XLA
   stream;
2. ``np.asarray(arr)`` inside a thread-pool executor — resolves the (already
   in-flight) transfer off the event loop, so many transfers and storage
   writes interleave under the scheduler's memory budget.

Serialization is zero-copy for every dtype in ``SUPPORTED_DTYPES`` (including
bfloat16/fp8 via ml_dtypes); anything else falls back to pickle (the
reference's ``torch.save`` fallback, ``tensor.py:66-69``).
"""

from __future__ import annotations

import asyncio
import logging
import math
import pickle
import time
from collections import deque
from concurrent.futures import Executor
from typing import Any, AsyncIterator, Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import d2h, telemetry
from ..io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from ..manifest import ArrayEntry
from ..serialization import (
    Serializer,
    array_as_bytes_view,
    array_from_bytes,
    array_nbytes,
    codec_for_raw_serializer,
    compress_framed,
    compress_payload,
    decode_framed_payload,
    decode_raw_payload,
    dtype_to_string,
    ensure_codec_available,
    is_raw_family,
    is_raw_serializable,
    raw_serializer_for_codec,
)
from ..utils import knobs

# Side-object suffix carrying a framed payload's compressed frame sizes
# (tiny JSON). Written by the same pipeline as the payload; read only by
# budgeted sub-reads (whole-object reads decode concatenated frames without
# a table).
FRAME_TABLE_SUFFIX = ".ftab"

logger = logging.getLogger(__name__)


def _is_jax_array(obj: Any) -> bool:
    import jax

    return isinstance(obj, jax.Array)


# The hint's single owner moved to ``d2h`` (the transfer lanes issue hints
# too); re-exported here for the existing importers (io_preparer, tests).
hint_copy_to_host = d2h.hint_copy_to_host


def chunk_row_ranges(
    shape, itemsize: int, max_chunk_bytes: int
) -> List[Tuple[int, int]]:
    """Row ranges [r0, r1) per dim-0 chunk, each chunk <= max_chunk_bytes
    (when a single row fits). Shared by the chunked-array preparer (one
    storage object per chunk) and the streaming stager (one chunk per
    streamed append into a single object)."""
    dim0 = int(shape[0])
    row_bytes = itemsize * int(np.prod(shape[1:])) if len(shape) > 1 else itemsize
    rows_per_chunk = max(1, max_chunk_bytes // max(row_bytes, 1))
    n_chunks = math.ceil(dim0 / rows_per_chunk)
    # Even spread so the last chunk isn't tiny.
    base = dim0 // n_chunks
    extra = dim0 % n_chunks
    ranges = []
    r0 = 0
    for i in range(n_chunks):
        rows = base + (1 if i < extra else 0)
        ranges.append((r0, r0 + rows))
        r0 += rows
    return ranges


def _silence_future(fut) -> None:
    """Retrieve (and drop) an abandoned lane resolve's outcome so asyncio
    never logs "exception was never retrieved" for work we cancelled."""
    if not fut.cancelled():
        fut.exception()


def to_host(arr: Any, executor: Optional[Executor] = None):
    """Kick off an async D2H transfer; return an awaitable resolver."""
    if _is_jax_array(arr):
        hint_copy_to_host(arr)

    async def resolve() -> np.ndarray:
        loop = asyncio.get_running_loop()
        if executor is not None:
            return await loop.run_in_executor(executor, np.asarray, arr)
        return np.asarray(arr)

    return resolve


async def _traced_to_host(
    arr: Any, executor: Optional[Executor], location: str, nbytes: int
) -> np.ndarray:
    """Resolve one device→host transfer, attributed as ``stage.d2h``.

    Inside a write pipeline (an active :class:`~..d2h.StagingContext`) the
    resolve runs on the DEDICATED transfer-lane executor — never queued
    behind serialize/compress jobs on the staging pool — and the lane
    records the transfer interval for the stage-time decomposition. Outside
    a pipeline it falls back to :func:`to_host` on the given executor, with
    a ``stage.d2h`` span when a telemetry session is active (free
    None-checks otherwise)."""
    ctx = d2h.get_active()
    if ctx is not None:
        loop = asyncio.get_running_loop()
        return await ctx.lanes.start(
            arr, nbytes, loop, times=ctx.times, location=location
        )
    tm = telemetry.get_active()
    if tm is None:
        return await to_host(arr, executor)()
    with tm.span("stage.d2h", "stage", path=location, nbytes=nbytes) as sp:
        host = await to_host(arr, executor)()
    tm.metrics.counter("d2h.bytes").add(nbytes)
    tm.metrics.histogram("d2h.seconds").observe(sp.span.dur or 0.0)
    return host


class ArrayBufferStager(BufferStager):
    def __init__(
        self,
        arr: Any,  # jax.Array | np.ndarray
        entry: ArrayEntry,
        is_async_snapshot: bool = False,
    ) -> None:
        self.arr = arr
        self.entry = entry
        self.is_async_snapshot = is_async_snapshot
        # Sole owner of level resolution, at construction (== prepare
        # time), never at stage time: a deferred background drain must not
        # re-read knobs whose env changed since (wrong level breaks the
        # fixed-level zstd determinism incremental dedup relies on; an
        # invalid ambient level would raise mid-drain).
        self.compression_level: Optional[int] = None
        if entry.serializer in (Serializer.RAW_ZSTD, Serializer.RAW_ZLIB):
            self.compression_level = knobs.get_compression_level(
                _codec=codec_for_raw_serializer(entry.serializer)
            )
        # Compressed frame sizes, published by stage_buffer for framed
        # entries; the companion FrameTableStager polls for it. A staging
        # failure publishes frame_error instead so the poller fails fast
        # rather than spinning as an orphaned task.
        self.frame_sizes: Optional[List[int]] = None
        self.frame_error: Optional[BaseException] = None
        # Set by the batcher when this request joins a member-framed
        # compressed slab: stage the RAW bytes (the slab compresses all
        # members together at the slab level); entry.serializer still
        # records the codec for the read side.
        self.stage_raw = False
        # First stream chunk's device slice, pre-hinted by start_d2h_hint
        # when this request will stream (see the note there).
        self._first_slice = None

    def rebind(self, arr: Any) -> None:
        """Point this stager at a new step's array and clear per-take state
        (frame publication, pre-hinted slices) while keeping the structural
        plan — entry, compression level, slab membership (``stage_raw``) —
        exactly as prepared. The prepared-state cache's hit path: the new
        array must match the cached plan's shape/dtype (guaranteed by the
        cache's fingerprint key)."""
        self.arr = arr
        self.frame_sizes = None
        self.frame_error = None
        self._first_slice = None

    def unbind(self) -> None:
        """Drop the array reference between takes so a cached prepared
        state never pins device/host buffers past its pipeline's commit."""
        self.arr = None
        self._first_slice = None

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        if not self.entry.frame_bytes:
            return await self._stage_inner(executor)
        try:
            return await self._stage_inner(executor)
        except BaseException as e:  # noqa: BLE001 - published, then re-raised
            # Any failure (D2H error, compressor OOM, cancellation) must
            # unblock the companion FrameTableStager's poll.
            self.frame_error = e
            raise

    async def _stage_inner(self, executor: Optional[Executor] = None) -> BufferType:
        # stage_raw (member of a compressed slab): the slab stager consumes
        # this buffer synchronously inside ITS staging call (copied into the
        # packed slab), so a zero-copy view is mutation-safe without the
        # async defensive copy below.
        serializer = Serializer.RAW if self.stage_raw else self.entry.serializer
        arr = self.arr
        if _is_jax_array(arr):
            host = await _traced_to_host(
                arr, executor, self.entry.location, _nbytes_of(arr)
            )
        else:
            host = np.asarray(arr)
            if (
                self.is_async_snapshot
                and serializer == Serializer.RAW
                and not self.stage_raw
            ):
                # Host arrays stage *before* async_take returns, but the RAW
                # staged buffer is a zero-copy view that the background
                # write reads after training resumed — copy so training can
                # mutate the live array meanwhile (reference
                # ``tensor.py:254-264``). Compressed/pickled payloads are
                # consumed synchronously inside this staging call and the
                # output is independent bytes, so they skip the copy.
                host = host.copy()
            elif not host.flags["C_CONTIGUOUS"]:
                host = np.ascontiguousarray(host)
        ctx = d2h.get_active()
        times = ctx.times if ctx is not None else None
        location = self.entry.location
        if serializer == Serializer.RAW:
            # Zero-copy fast path: the staged buffer IS a memoryview of the
            # resolved host buffer — no serialization pass, no intermediate
            # bytes(). Downstream (write_stream appends, plugin writes, the
            # digest fold, slab packing) all consume the buffer protocol
            # directly, so the only full sweeps over a RAW payload are the
            # transfer itself, the (optional) hash, and the storage write.
            t0 = time.monotonic()
            view = array_as_bytes_view(host)
            if times is not None:
                times.record(
                    "serialize", t0, time.monotonic(),
                    path=location, nbytes=view.nbytes,
                )
            return view
        if is_raw_family(self.entry.serializer):
            # Compress on the executor: seconds of zstd on a large shard
            # must not block the event loop that dispatches every other
            # request's transfers and writes.
            view = array_as_bytes_view(host)
            level = self.compression_level
            loop = asyncio.get_running_loop()
            if self.entry.frame_bytes:
                def framed():
                    t0 = time.monotonic()
                    payload, sizes = compress_framed(
                        view,
                        self.entry.serializer,
                        level,
                        self.entry.frame_bytes,
                    )
                    if times is not None:
                        times.record(
                            "serialize", t0, time.monotonic(),
                            path=location, nbytes=len(payload),
                        )
                    # Publish for the companion FrameTableStager (same
                    # pipeline, polls until this lands). Cross-thread by
                    # design: a single atomic reference store, and the
                    # loop-side assignment in stage_chunks is a mutually
                    # exclusive path (a request stages whole OR streamed,
                    # never both).
                    self.frame_sizes = sizes  # noqa: TSA701
                    return payload

                if executor is not None:
                    return await loop.run_in_executor(executor, framed)
                return framed()

            def compress():
                t0 = time.monotonic()
                payload = compress_payload(view, self.entry.serializer, level)
                if times is not None:
                    times.record(
                        "serialize", t0, time.monotonic(),
                        path=location, nbytes=len(payload),
                    )
                return payload

            if executor is not None:
                return await loop.run_in_executor(executor, compress)
            return compress()
        t0 = time.monotonic()
        payload = pickle.dumps(host, protocol=pickle.HIGHEST_PROTOCOL)
        if times is not None:
            times.record(
                "serialize", t0, time.monotonic(),
                path=location, nbytes=len(payload),
            )
        return payload

    def get_staging_cost_bytes(self) -> int:
        if not is_raw_family(self.entry.serializer):
            return _nbytes_of(self.arr)
        nbytes = array_nbytes(self.entry.shape, self.entry.dtype)
        if self.entry.serializer != Serializer.RAW:
            # Peak transient footprint holds the raw host bytes AND the
            # compressed output simultaneously; incompressible data makes
            # that ~2x raw — the budget must see the true peak.
            return 2 * nbytes
        return nbytes

    # -- streaming protocol --------------------------------------------------

    def _stream_row_ranges(self) -> List[Tuple[int, int]]:
        shape = self.entry.shape
        if not shape or int(shape[0]) < 2:
            return []
        itemsize = entry_np_dtype(self.entry.dtype, self.entry.serializer).itemsize
        return chunk_row_ranges(shape, itemsize, knobs.get_stream_chunk_bytes())

    def can_stream(self) -> bool:
        if self.stage_raw:
            # Slab members are consumed synchronously by the slab's own
            # stager; the SLAB streams (or not), never the member.
            return False
        serializer = self.entry.serializer
        if serializer == Serializer.RAW:
            pass
        elif is_raw_family(serializer) and self.entry.frame_bytes:
            # Framed compression: frames are independent, so chunk-local
            # compression concatenates to the identical payload.
            pass
        else:
            # Pickle and single-blob compressed payloads need the whole
            # buffer in one call.
            return False
        if self.is_async_snapshot and not _is_jax_array(self.arr):
            # Mutable host source on an async take: capture semantics
            # require a private buffer before async_take returns; a stream
            # keeps reading the live array long after training resumed.
            return False
        return len(self._stream_row_ranges()) > 1

    async def stage_chunks(
        self, executor: Optional[Executor] = None
    ) -> AsyncIterator[BufferType]:
        """Dim-0 chunk stream whose concatenation is byte-identical to
        :meth:`stage_buffer`'s output.

        Inside a write pipeline the upcoming chunks' transfers run on the
        PARALLEL D2H LANES: each chunk the lane window admits is hinted
        (``copy_to_host_async``) and starts resolving on the transfer
        executor immediately, so several transfers stream back-to-back
        while this coroutine serializes/yields earlier chunks — look-ahead
        depth is bounded by ``TORCHSNAPSHOT_TPU_D2H_WINDOW_BYTES`` (debited
        against the pipeline's memory budget), not a fixed chunk count.
        Outside a pipeline, the round-3 two-ahead hint chain is kept.
        RAW chunks are yielded as zero-copy memoryviews of the resolved
        host buffers. Framed compression emits whole ``frame_bytes`` frames
        and carries the inter-chunk remainder, so the frame layout (and the
        published ``frame_sizes``) matches the non-streamed path exactly."""
        serializer = self.entry.serializer
        framed = serializer != Serializer.RAW
        ctx = d2h.get_active()
        times = ctx.times if ctx is not None else None
        lanes = ctx.lanes if ctx is not None else None
        location = self.entry.location
        # Lane-resolving look-ahead: (host-array future, admitted bytes).
        pending: deque = deque()
        try:
            ranges = self._stream_row_ranges()
            arr = self.arr
            is_jax = _is_jax_array(arr)
            host_full: Optional[np.ndarray] = None
            if not is_jax:
                host_full = np.asarray(arr)
                if not host_full.flags["C_CONTIGUOUS"]:
                    host_full = np.ascontiguousarray(host_full)
            loop = asyncio.get_running_loop()
            level = self.compression_level
            frame_bytes = self.entry.frame_bytes
            carry = bytearray()  # raw tail short of a full compression frame
            sizes: List[int] = []
            first_slice = self._first_slice
            self._first_slice = None
            if first_slice is not None and (
                not ranges
                or int(first_slice.shape[0]) != ranges[0][1] - ranges[0][0]
            ):
                # Chunk knob changed between capture and drain: the
                # pre-hinted slice no longer matches the first range.
                first_slice = None
            itemsize = entry_np_dtype(self.entry.dtype, serializer).itemsize
            row_bytes = (
                itemsize * int(np.prod(self.entry.shape[1:]))
                if len(self.entry.shape) > 1
                else itemsize
            )
            next_i = 0  # next range index to enter the look-ahead

            def pump() -> None:
                # Fill the lane window with upcoming chunks: hint + start
                # resolving each one the window (and budget headroom)
                # admits. The first look-ahead chunk of an empty stream is
                # force-admitted so a window smaller than one chunk
                # degrades to one-ahead, never to a stall.
                nonlocal next_i, first_slice
                while next_i < len(ranges):
                    nr0, nr1 = ranges[next_i]
                    est = (nr1 - nr0) * row_bytes
                    if not lanes.try_admit(est, force=not pending):
                        break
                    if first_slice is not None:
                        s, skip_hint = first_slice, True
                        first_slice = None
                    else:
                        s, skip_hint = arr[nr0:nr1], False
                    pending.append(
                        (
                            lanes.start(
                                s,
                                est,
                                loop,
                                times=times,
                                location=location,
                                skip_hint=skip_hint,
                            ),
                            est,
                        )
                    )
                    next_i += 1

            # Legacy (no active pipeline) look-ahead: pre-hinted device
            # slices, two chunks ahead of the resolve so transfers pipeline
            # on high-latency links. Each hinted slice caches its host
            # bytes, so the look-ahead is part of the stream's footprint.
            hinted: deque = deque()
            if lanes is None and first_slice is not None:
                hinted.append(first_slice)
                first_slice = None
            _HINT_AHEAD = 2
            for i, (r0, r1) in enumerate(ranges):
                if is_jax:
                    if lanes is not None:
                        pump()
                        fut, est = pending.popleft()
                        # Release the window reservation before resolving:
                        # from here the chunk's bytes are covered by the
                        # stream's own per-chunk budget debit
                        # (scheduler._stream_one), and the freed window
                        # immediately admits the next look-ahead transfer.
                        lanes.release(est)
                        host = await fut
                        pump()
                    else:
                        while len(hinted) < _HINT_AHEAD + 1 and i + len(
                            hinted
                        ) < len(ranges):
                            nr0, nr1 = ranges[i + len(hinted)]
                            s = arr[nr0:nr1]
                            hint_copy_to_host(s)
                            hinted.append(s)
                        cur = hinted.popleft()
                        host = await _traced_to_host(
                            cur, executor, location, _nbytes_of(cur)
                        )
                else:
                    host = host_full[r0:r1]
                # Contiguity (the only copy a RAW chunk can ever pay) is
                # owned by array_as_bytes_view — one pass, zero when the
                # device layout is already C-order.
                t0 = time.monotonic()
                view = array_as_bytes_view(host)
                if not framed:
                    if times is not None:
                        times.record(
                            "serialize", t0, time.monotonic(),
                            path=location, nbytes=view.nbytes,
                        )
                    yield view
                    continue
                carry.extend(view)
                nframes = len(carry) // frame_bytes
                if i + 1 == len(ranges):
                    # Last chunk: flush everything, incl. the short tail.
                    block = bytes(carry)
                    del carry[:]
                elif nframes == 0:
                    continue
                else:
                    block = bytes(memoryview(carry)[: nframes * frame_bytes])
                    del carry[: nframes * frame_bytes]

                def compress_block(block=block):
                    t0 = time.monotonic()
                    out = compress_framed(block, serializer, level, frame_bytes)
                    if times is not None:
                        times.record(
                            "serialize", t0, time.monotonic(),
                            path=location, nbytes=len(out[0]),
                        )
                    return out

                if executor is not None:
                    payload, fsizes = await loop.run_in_executor(
                        executor, compress_block
                    )
                else:
                    payload, fsizes = compress_block()
                sizes.extend(fsizes)
                if payload:
                    yield payload
            if framed:
                # Publish for the companion FrameTableStager (same pipeline).
                self.frame_sizes = sizes
        except BaseException as e:  # noqa: BLE001 - published, then re-raised
            if self.entry.frame_bytes:
                self.frame_error = e
            raise
        finally:
            # Abandoned look-ahead (mid-stream failure, aclose from an
            # aborting pipeline): release every window admission so the
            # budget balances, and silence the orphaned resolves.
            while pending:
                fut, est = pending.popleft()
                fut.cancel()
                fut.add_done_callback(_silence_future)
                lanes.release(est)

    def start_d2h_hint(self) -> None:
        if not _is_jax_array(self.arr):
            return
        if knobs.is_stream_writes_enabled() and self.can_stream():
            # This request will (almost certainly) stream: hint only the
            # FIRST stream chunk's slice. Hinting the whole array would pull
            # every byte into jax's host cache AND the per-chunk slices
            # would transfer again at stage time — 2x the link traffic on
            # exactly the drains streaming exists to speed up. The later
            # chunks hint themselves one ahead inside stage_chunks; host
            # RAM stays bounded by the stream depth instead of the eager
            # whole-state prefetch.
            if self._first_slice is None:
                r0, r1 = self._stream_row_ranges()[0]
                self._first_slice = self.arr[r0:r1]
                hint_copy_to_host(self._first_slice)
            return
        hint_copy_to_host(self.arr)


class PollingTableStager(BufferStager):
    """Base for ``.ftab`` side-object stagers: polls a main stager's
    published ``frame_sizes`` and encodes a JSON table.

    The sizes exist only after the main stager compressed the payload (which
    is why they can't live in the manifest — it is gathered before staging),
    so this stager polls the main stager's published result. Both requests
    run in the same pipeline; the poll holds no executor thread and the main
    request always runs (dedup link-in decisions happen after staging), so
    this terminates. The generous deadline guards that invariant: if a
    future change ever drops/filters the payload req from this rank's
    pipeline, fail loudly with the payload location instead of hanging the
    pipeline forever (ADVICE round 3, item 2).
    """

    POLL_TIMEOUT_S = 1800.0

    def __init__(self, main: Any, described: str) -> None:
        self.main = main  # must expose frame_sizes / frame_error
        self.described = described

    def _table(self) -> dict:
        raise NotImplementedError

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        import json
        import time

        deadline = time.monotonic() + self.POLL_TIMEOUT_S
        while self.main.frame_sizes is None:
            if self.main.frame_error is not None:
                raise RuntimeError(
                    f"frame table for {self.described} unavailable: "
                    "payload staging failed"
                ) from self.main.frame_error
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"frame table for {self.described} never materialized: "
                    "the payload write request did not stage within the "
                    "deadline — was it dropped from this rank's pipeline?"
                )
            await asyncio.sleep(0.005)
        return json.dumps(self._table()).encode()

    def get_staging_cost_bytes(self) -> int:
        # ~8 digits per frame size; a 4 GB payload at 8 MiB frames is ~4 KB.
        return 16384

    def start_d2h_hint(self) -> None:
        pass  # no device data of its own


class FrameTableStager(PollingTableStager):
    """``.ftab`` of a uniformly framed payload: ``{"frame_bytes", "sizes"}``."""

    def __init__(self, main: ArrayBufferStager) -> None:
        super().__init__(main, described=main.entry.location)

    def _table(self) -> dict:
        return {
            "frame_bytes": self.main.entry.frame_bytes,
            "sizes": self.main.frame_sizes,
        }


def plan_frame_groups(
    frame_sizes: Sequence[int],
    frame_bytes: int,
    raw_begin: int,
    raw_end: int,
    budget: Optional[int],
) -> List[Tuple[int, int, int, int]]:
    """Split the raw range [raw_begin, raw_end) into frame-aligned groups.

    Returns ``(comp_begin, comp_end, group_raw_begin, group_raw_end)`` per
    group, where the comp range indexes the concatenated framed payload and
    each group's raw coverage is <= max(budget, frame_bytes) (a single frame
    wider than the budget is admitted whole — the usual one-over-budget
    escape hatch).
    """
    prefix = [0]
    for s in frame_sizes:
        prefix.append(prefix[-1] + int(s))
    first = raw_begin // frame_bytes
    last = (raw_end + frame_bytes - 1) // frame_bytes  # exclusive
    per_group = max(1, (budget or raw_end) // frame_bytes)
    groups: List[Tuple[int, int, int, int]] = []
    i = first
    while i < last:
        j = min(i + per_group, last)
        groups.append(
            (prefix[i], prefix[j], i * frame_bytes, min(j * frame_bytes, raw_end))
        )
        i = j
    return groups


class FramedSliceConsumer(BufferConsumer):
    """Decompresses one group of frames and delivers the requested raw slice.

    ``deliver`` receives a memoryview of raw bytes covering
    [raw_begin, raw_end) of the entry's serialized layout; the group's
    frames may cover a superset (frame alignment), which is sliced off.
    """

    # Read-merging must never coalesce a BIG array's framed groups: their
    # COMPRESSED ranges are adjacent, so a compressed-span cap would
    # re-create the whole-object decode the budget split exists to avoid.
    # Checked (via any wrapper's proxy) by ``batcher.batch_read_requests``.
    # Member-framed SLAB reads opt out (``merge_exempt=False``): each
    # member decodes independently, so adjacent members' compressed ranges
    # merge into one ranged read safely.
    merge_exempt = True

    def __init__(
        self,
        serializer: str,
        group_raw_begin: int,
        raw_begin: int,
        raw_end: int,
        deliver: Callable[[memoryview], None],
        decoded_raw_bytes: Optional[int] = None,
        merge_exempt: bool = True,
    ) -> None:
        self.serializer = serializer
        self.group_raw_begin = group_raw_begin
        self.raw_begin = raw_begin
        self.raw_end = raw_end
        self.deliver = deliver
        # Frame alignment can force decoding more raw bytes than the
        # requested slice; the budget must see the true peak.
        self.decoded_raw_bytes = decoded_raw_bytes
        self.merge_exempt = merge_exempt

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        def work() -> None:
            raw = decode_framed_payload(buf, self.serializer)
            off = self.raw_begin - self.group_raw_begin
            self.deliver(
                memoryview(raw)[off : off + (self.raw_end - self.raw_begin)]
            )

        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, work)
        else:
            work()

    def get_consuming_cost_bytes(self) -> int:
        # Compressed group + decompressed raw coexist during decode.
        return 2 * (self.decoded_raw_bytes or (self.raw_end - self.raw_begin))


def _flat_range_deliver(target: np.ndarray, begin: int, end: int):
    flat = target.view(np.uint8).reshape(-1)

    def deliver(mv: memoryview) -> None:
        flat[begin:end] = np.frombuffer(mv, dtype=np.uint8)

    return deliver


def _nbytes_of(arr: Any) -> int:
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    return int(np.asarray(arr).nbytes)


def entry_np_dtype(dtype: str, serializer: str) -> np.dtype:
    """Numpy dtype for an entry: raw-family entries use the canonical table;
    pickle entries recorded ``str(np.dtype)`` (e.g. ``datetime64[D]``,
    ``object``)."""
    from ..serialization import string_to_dtype

    if is_raw_family(serializer):
        return string_to_dtype(dtype)
    return np.dtype(dtype)


def entry_cost_bytes(entry: ArrayEntry) -> int:
    """Best-effort host-memory cost of staging/consuming one array entry.

    Compressed entries cost ~2x on the consume side: the compressed buffer
    and the decoded raw bytes coexist during decompression.
    """
    try:
        n = 1
        for d in entry.shape:
            n *= int(d)
        n *= entry_np_dtype(entry.dtype, entry.serializer).itemsize
        if is_raw_family(entry.serializer) and entry.serializer != Serializer.RAW:
            n *= 2
        return n
    except Exception:
        return 1024 * 1024


class ArrayBufferConsumer(BufferConsumer):
    """Deserializes one buffer and copies it into a host target buffer."""

    def __init__(self, target: np.ndarray, entry: ArrayEntry) -> None:
        self.target = target  # writable, C-contiguous host array
        self.entry = entry

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        def work() -> None:
            if is_raw_family(self.entry.serializer):
                decode = (
                    decode_framed_payload
                    if self.entry.frame_bytes
                    else decode_raw_payload
                )
                raw = decode(buf, self.entry.serializer)
                src = array_from_bytes(raw, self.entry.dtype, self.entry.shape)
            else:
                src = pickle.loads(bytes(buf))
            np.copyto(self.target, src, casting="no")

        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, work)
        else:
            work()

    def get_consuming_cost_bytes(self) -> int:
        return entry_cost_bytes(self.entry)


class ChunkedReadConsumer(BufferConsumer):
    """Consumes one byte-range of a raw-serialized array into the flat target.

    Enables budget-capped reads of arrays larger than host memory allows at
    once (reference ``tensor.py:120-166``; exercised by ``read_object`` with
    ``memory_budget_bytes``).
    """

    def __init__(self, target: np.ndarray, byte_range: Tuple[int, int]) -> None:
        self.target = target
        self.byte_range = byte_range

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        begin, end = self.byte_range
        flat = self.target.view(np.uint8).reshape(-1)

        def work() -> None:
            flat[begin:end] = np.frombuffer(memoryview(buf), dtype=np.uint8)

        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, work)
        else:
            work()

    def get_consuming_cost_bytes(self) -> int:
        return self.byte_range[1] - self.byte_range[0]


def _member_deliver(target: np.ndarray, entry: ArrayEntry):
    """Deliver one slab member's raw bytes into the host target."""

    def deliver(mv: memoryview) -> None:
        src = array_from_bytes(mv, entry.dtype, entry.shape)
        np.copyto(target, src, casting="no")

    return deliver


def _member_framed_reads(
    entry: ArrayEntry, target: np.ndarray, frame_table
) -> List[ReadReq]:
    """Read one member of a member-framed compressed slab.

    With the slab's ``.ftab`` (``{"raw_sizes": [...], "sizes": [...]}``) the
    member's raw range resolves to its covering frames and a compressed
    byte-range read; without it (side object lost), degrade to reading and
    decoding the WHOLE slab and slicing the member out — slower, never a
    failed restore."""
    a, b = entry.raw_range
    if isinstance(frame_table, dict):
        raw_sizes = frame_table["raw_sizes"]
        comp_sizes = frame_table["sizes"]
        rprefix, cprefix = [0], [0]
        for r in raw_sizes:
            rprefix.append(rprefix[-1] + int(r))
        for c in comp_sizes:
            cprefix.append(cprefix[-1] + int(c))
        # Covering frame run [i, j): frames are member-aligned, so a lands
        # on a frame boundary for well-formed manifests; tolerate interior
        # starts anyway.
        i = max(0, next((k for k in range(len(raw_sizes)) if rprefix[k + 1] > a), 0))
        j = next(
            (k + 1 for k in range(i, len(raw_sizes)) if rprefix[k + 1] >= b),
            len(raw_sizes),
        )
        return [
            ReadReq(
                path=entry.location,
                buffer_consumer=FramedSliceConsumer(
                    entry.serializer,
                    group_raw_begin=rprefix[i],
                    raw_begin=a,
                    raw_end=b,
                    deliver=_member_deliver(target, entry),
                    decoded_raw_bytes=rprefix[j] - rprefix[i],
                    merge_exempt=False,
                ),
                byte_range=(cprefix[i], cprefix[j]),
            )
        ]
    return [
        ReadReq(
            path=entry.location,
            buffer_consumer=FramedSliceConsumer(
                entry.serializer,
                group_raw_begin=0,
                raw_begin=a,
                raw_end=b,
                deliver=_member_deliver(target, entry),
                # The whole slab decodes per member here; without the table
                # its raw extent is unknown, so bill the slab threshold
                # (slabs close at it) — over-billing serializes these
                # degraded reads through the budget instead of letting N
                # concurrent whole-slab decodes blow past it.
                decoded_raw_bytes=max(
                    knobs.get_slab_size_threshold_bytes(), b - a
                ),
            ),
        )
    ]


class ArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        arr: Any,
        replicated: bool = False,
        is_async_snapshot: bool = False,
    ) -> Tuple[ArrayEntry, List[WriteReq]]:
        host_like = arr  # dtype/shape probes work on jax and numpy alike
        dtype = np.dtype(host_like.dtype)
        if is_raw_serializable(dtype):
            serializer = raw_serializer_for_codec(knobs.get_compression())
        else:
            serializer = Serializer.PICKLE
        frame_bytes = None
        if serializer in (Serializer.RAW_ZSTD, Serializer.RAW_ZLIB):
            f = knobs.get_compression_frame_bytes()
            raw_nbytes = array_nbytes(
                list(host_like.shape), dtype_to_string(dtype)
            )
            if f > 0 and raw_nbytes > f:
                frame_bytes = f
        entry = ArrayEntry(
            location=storage_path,
            serializer=serializer,
            dtype=dtype_to_string(dtype) if is_raw_family(serializer) else str(dtype),
            shape=list(host_like.shape),
            replicated=replicated,
            frame_bytes=frame_bytes,
        )
        stager = ArrayBufferStager(arr, entry, is_async_snapshot)
        reqs = [WriteReq(path=storage_path, buffer_stager=stager)]
        if frame_bytes:
            reqs.append(
                WriteReq(
                    path=storage_path + FRAME_TABLE_SUFFIX,
                    buffer_stager=FrameTableStager(stager),
                )
            )
        return entry, reqs

    @staticmethod
    def prepare_read(  # spmd-pure
        entry: ArrayEntry,
        target: np.ndarray,
        buffer_size_limit_bytes: Optional[int] = None,
        frame_table: Optional[List[int]] = None,
    ) -> List[ReadReq]:
        """Plan reads filling ``target`` (a writable host array).

        ``frame_table`` (the compressed frame sizes from the entry's
        ``.ftab`` side object) enables budgeted sub-reads of framed
        compressed entries: each read fetches one group of frames and
        decompresses only those. For member-framed slab members
        (``entry.raw_range``) the table is a dict carrying per-frame raw AND
        compressed sizes; the member's raw range maps to exactly its own
        covering frames.
        """
        ensure_codec_available(entry.serializer)
        if getattr(entry, "raw_range", None) is not None:
            return _member_framed_reads(entry, target, frame_table)
        if (
            entry.frame_bytes
            and frame_table is not None
            and buffer_size_limit_bytes is not None
            and array_nbytes(entry.shape, entry.dtype) > buffer_size_limit_bytes
        ):
            base = entry.byte_range[0] if entry.byte_range else 0
            raw_total = array_nbytes(entry.shape, entry.dtype)
            reqs = []
            for cb, ce, grb, gre in plan_frame_groups(
                frame_table,
                entry.frame_bytes,
                0,
                raw_total,
                buffer_size_limit_bytes,
            ):
                reqs.append(
                    ReadReq(
                        path=entry.location,
                        buffer_consumer=FramedSliceConsumer(
                            entry.serializer,
                            group_raw_begin=grb,
                            raw_begin=grb,
                            raw_end=gre,
                            deliver=_flat_range_deliver(target, grb, gre),
                        ),
                        byte_range=(base + cb, base + ce),
                    )
                )
            return reqs
        if entry.serializer != Serializer.RAW:
            # Pickled and (unframed, or unbudgeted) compressed payloads:
            # read the whole object, ranged only to a slab-relocated span if
            # the entry records one.
            return [
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ArrayBufferConsumer(target, entry),
                    byte_range=tuple(entry.byte_range)
                    if entry.byte_range
                    else None,
                )
            ]
        base_range = entry.byte_range or [0, array_nbytes(entry.shape, entry.dtype)]
        total = base_range[1] - base_range[0]
        if buffer_size_limit_bytes is None or total <= buffer_size_limit_bytes:
            return [
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ArrayBufferConsumer(target, entry),
                    byte_range=(base_range[0], base_range[1]),
                )
            ]
        # Budget-capped: split into byte-range reads landing directly in the
        # target's flat view. Ranges are itemsize-aligned by construction.
        itemsize = target.dtype.itemsize
        per_read = max(
            itemsize, buffer_size_limit_bytes - buffer_size_limit_bytes % itemsize
        )
        read_reqs = []
        for begin in range(0, total, per_read):
            end = min(begin + per_read, total)
            read_reqs.append(
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ChunkedReadConsumer(target, (begin, end)),
                    byte_range=(base_range[0] + begin, base_range[0] + end),
                )
            )
        return read_reqs


