"""Array write/read preparation: the D2H + serialization hot path.

TPU-native analogue of the reference's ``io_preparers/tensor.py:45-376``. The
reference's performance trick is overlapping CUDA D2H copies (run on a
GIL-dropping jit-scripted helper inside a thread pool) with storage I/O; the
XLA-native equivalent used here is:

1. ``jax.Array.copy_to_host_async()`` at the start of staging — enqueues the
   transfer on the device without blocking the Python thread or the XLA
   stream;
2. ``np.asarray(arr)`` inside a thread-pool executor — resolves the (already
   in-flight) transfer off the event loop, so many transfers and storage
   writes interleave under the scheduler's memory budget.

Serialization is zero-copy for every dtype in ``SUPPORTED_DTYPES`` (including
bfloat16/fp8 via ml_dtypes); anything else falls back to pickle (the
reference's ``torch.save`` fallback, ``tensor.py:66-69``).
"""

from __future__ import annotations

import asyncio
import math
import pickle
from concurrent.futures import Executor
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from ..manifest import ArrayEntry
from ..serialization import (
    Serializer,
    array_as_bytes_view,
    array_from_bytes,
    array_nbytes,
    codec_for_raw_serializer,
    compress_payload,
    decode_raw_payload,
    dtype_to_string,
    ensure_codec_available,
    is_raw_family,
    is_raw_serializable,
    raw_serializer_for_codec,
)
from ..utils import knobs


def _is_jax_array(obj: Any) -> bool:
    import jax

    return isinstance(obj, jax.Array)


def to_host(arr: Any, executor: Optional[Executor] = None):
    """Kick off an async D2H transfer; return an awaitable resolver."""
    if _is_jax_array(arr):
        try:
            arr.copy_to_host_async()
        except Exception:
            pass  # some platforms lack the async hint; np.asarray still works

    async def resolve() -> np.ndarray:
        loop = asyncio.get_event_loop()
        if executor is not None:
            return await loop.run_in_executor(executor, np.asarray, arr)
        return np.asarray(arr)

    return resolve


class ArrayBufferStager(BufferStager):
    def __init__(
        self,
        arr: Any,  # jax.Array | np.ndarray
        entry: ArrayEntry,
        is_async_snapshot: bool = False,
    ) -> None:
        self.arr = arr
        self.entry = entry
        self.is_async_snapshot = is_async_snapshot
        # Sole owner of level resolution, at construction (== prepare
        # time), never at stage time: a deferred background drain must not
        # re-read knobs whose env changed since (wrong level breaks the
        # fixed-level zstd determinism incremental dedup relies on; an
        # invalid ambient level would raise mid-drain).
        self.compression_level: Optional[int] = None
        if entry.serializer in (Serializer.RAW_ZSTD, Serializer.RAW_ZLIB):
            self.compression_level = knobs.get_compression_level(
                _codec=codec_for_raw_serializer(entry.serializer)
            )

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        arr = self.arr
        if _is_jax_array(arr):
            host = await to_host(arr, executor)()
        else:
            host = np.asarray(arr)
            if self.is_async_snapshot and self.entry.serializer == Serializer.RAW:
                # Host arrays stage *before* async_take returns, but the RAW
                # staged buffer is a zero-copy view that the background
                # write reads after training resumed — copy so training can
                # mutate the live array meanwhile (reference
                # ``tensor.py:254-264``). Compressed/pickled payloads are
                # consumed synchronously inside this staging call and the
                # output is independent bytes, so they skip the copy.
                host = host.copy()
            elif not host.flags["C_CONTIGUOUS"]:
                host = np.ascontiguousarray(host)
        if self.entry.serializer == Serializer.RAW:
            return array_as_bytes_view(host)
        if is_raw_family(self.entry.serializer):
            # Compress on the executor: seconds of zstd on a large shard
            # must not block the event loop that dispatches every other
            # request's transfers and writes.
            view = array_as_bytes_view(host)
            level = self.compression_level
            loop = asyncio.get_event_loop()
            if executor is not None:
                return await loop.run_in_executor(
                    executor, compress_payload, view, self.entry.serializer, level
                )
            return compress_payload(view, self.entry.serializer, level)
        return pickle.dumps(host, protocol=pickle.HIGHEST_PROTOCOL)

    def get_staging_cost_bytes(self) -> int:
        if not is_raw_family(self.entry.serializer):
            return _nbytes_of(self.arr)
        nbytes = array_nbytes(self.entry.shape, self.entry.dtype)
        if self.entry.serializer != Serializer.RAW:
            # Peak transient footprint holds the raw host bytes AND the
            # compressed output simultaneously; incompressible data makes
            # that ~2x raw — the budget must see the true peak.
            return 2 * nbytes
        return nbytes

    def start_d2h_hint(self) -> None:
        if _is_jax_array(self.arr):
            try:
                self.arr.copy_to_host_async()
            except Exception:  # pragma: no cover - platform-specific hint
                pass


def _nbytes_of(arr: Any) -> int:
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    return int(np.asarray(arr).nbytes)


def entry_np_dtype(dtype: str, serializer: str) -> np.dtype:
    """Numpy dtype for an entry: raw-family entries use the canonical table;
    pickle entries recorded ``str(np.dtype)`` (e.g. ``datetime64[D]``,
    ``object``)."""
    from ..serialization import string_to_dtype

    if is_raw_family(serializer):
        return string_to_dtype(dtype)
    return np.dtype(dtype)


def entry_cost_bytes(entry: ArrayEntry) -> int:
    """Best-effort host-memory cost of staging/consuming one array entry.

    Compressed entries cost ~2x on the consume side: the compressed buffer
    and the decoded raw bytes coexist during decompression.
    """
    try:
        n = 1
        for d in entry.shape:
            n *= int(d)
        n *= entry_np_dtype(entry.dtype, entry.serializer).itemsize
        if is_raw_family(entry.serializer) and entry.serializer != Serializer.RAW:
            n *= 2
        return n
    except Exception:
        return 1024 * 1024


class ArrayBufferConsumer(BufferConsumer):
    """Deserializes one buffer and copies it into a host target buffer."""

    def __init__(self, target: np.ndarray, entry: ArrayEntry) -> None:
        self.target = target  # writable, C-contiguous host array
        self.entry = entry

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        def work() -> None:
            if is_raw_family(self.entry.serializer):
                raw = decode_raw_payload(buf, self.entry.serializer)
                src = array_from_bytes(raw, self.entry.dtype, self.entry.shape)
            else:
                src = pickle.loads(bytes(buf))
            np.copyto(self.target, src, casting="no")

        loop = asyncio.get_event_loop()
        if executor is not None:
            await loop.run_in_executor(executor, work)
        else:
            work()

    def get_consuming_cost_bytes(self) -> int:
        return entry_cost_bytes(self.entry)


class ChunkedReadConsumer(BufferConsumer):
    """Consumes one byte-range of a raw-serialized array into the flat target.

    Enables budget-capped reads of arrays larger than host memory allows at
    once (reference ``tensor.py:120-166``; exercised by ``read_object`` with
    ``memory_budget_bytes``).
    """

    def __init__(self, target: np.ndarray, byte_range: Tuple[int, int]) -> None:
        self.target = target
        self.byte_range = byte_range

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        begin, end = self.byte_range
        flat = self.target.view(np.uint8).reshape(-1)

        def work() -> None:
            flat[begin:end] = np.frombuffer(memoryview(buf), dtype=np.uint8)

        loop = asyncio.get_event_loop()
        if executor is not None:
            await loop.run_in_executor(executor, work)
        else:
            work()

    def get_consuming_cost_bytes(self) -> int:
        return self.byte_range[1] - self.byte_range[0]


class ArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        arr: Any,
        replicated: bool = False,
        is_async_snapshot: bool = False,
    ) -> Tuple[ArrayEntry, List[WriteReq]]:
        host_like = arr  # dtype/shape probes work on jax and numpy alike
        dtype = np.dtype(host_like.dtype)
        if is_raw_serializable(dtype):
            serializer = raw_serializer_for_codec(knobs.get_compression())
        else:
            serializer = Serializer.PICKLE
        entry = ArrayEntry(
            location=storage_path,
            serializer=serializer,
            dtype=dtype_to_string(dtype) if is_raw_family(serializer) else str(dtype),
            shape=list(host_like.shape),
            replicated=replicated,
        )
        stager = ArrayBufferStager(arr, entry, is_async_snapshot)
        return entry, [WriteReq(path=storage_path, buffer_stager=stager)]

    @staticmethod
    def prepare_read(
        entry: ArrayEntry,
        target: np.ndarray,
        buffer_size_limit_bytes: Optional[int] = None,
    ) -> List[ReadReq]:
        """Plan reads filling ``target`` (a writable host array)."""
        ensure_codec_available(entry.serializer)
        if entry.serializer != Serializer.RAW:
            # Pickled and compressed payloads have no raw byte layout on
            # storage: read the whole object (never budget-chunked), ranged
            # only to a slab-relocated span if the entry records one.
            return [
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ArrayBufferConsumer(target, entry),
                    byte_range=tuple(entry.byte_range)
                    if entry.byte_range
                    else None,
                )
            ]
        base_range = entry.byte_range or [0, array_nbytes(entry.shape, entry.dtype)]
        total = base_range[1] - base_range[0]
        if buffer_size_limit_bytes is None or total <= buffer_size_limit_bytes:
            return [
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ArrayBufferConsumer(target, entry),
                    byte_range=(base_range[0], base_range[1]),
                )
            ]
        # Budget-capped: split into byte-range reads landing directly in the
        # target's flat view. Ranges are itemsize-aligned by construction.
        itemsize = target.dtype.itemsize
        per_read = max(
            itemsize, buffer_size_limit_bytes - buffer_size_limit_bytes % itemsize
        )
        read_reqs = []
        for begin in range(0, total, per_read):
            end = min(begin + per_read, total)
            read_reqs.append(
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ChunkedReadConsumer(target, (begin, end)),
                    byte_range=(base_range[0] + begin, base_range[0] + end),
                )
            )
        return read_reqs


