"""GSPMD-sharded array save/restore with arbitrary resharding on load.

This is the elasticity engine — the TPU-native analogue of the reference's
``io_preparers/sharded_tensor.py:46-320``, re-derived for ``jax.Array``:

- **Save**: every process saves its *addressable* shards whose global
  ``replica_id == 0``, so each distinct shard of the global array is written
  exactly once across the whole pod, regardless of how the sharding mixes
  model- and data-parallel axes. Shard coordinates are global
  ``(offsets, sizes)`` derived from ``jax.Array.addressable_shards[i].index``.
  Shards larger than the knob-configured max are subdivided along their
  largest dimension for pipelining (reference ``subdivide_shard:46``).
- **Restore**: the target's sharding (from the live array being restored, or
  any ``NamedSharding`` the caller provides) is decomposed the same way; for
  every saved shard that overlaps a local target shard we issue one read and
  scatter the overlapping hyper-rectangles into all destination buffers
  (reference ``:228-269``). Saved and target shardings need not match in mesh
  shape, axis order, or process count — this is what makes snapshots elastic.
"""

from __future__ import annotations

import asyncio
import pickle
from concurrent.futures import Executor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import hashing
from ..io_types import BufferConsumer, BufferType, ReadReq, WriteReq
from ..manifest import ArrayEntry, Shard, ShardedArrayEntry
from ..serialization import (
    Serializer,
    array_from_bytes,
    decode_framed_payload,
    decode_raw_payload,
    ensure_codec_available,
    is_raw_family,
    string_to_dtype,
)
from ..utils import knobs
from .array import ArrayIOPreparer, FramedSliceConsumer

# A target to restore into: (host buffer, global offsets, sizes)
TargetShard = Tuple[np.ndarray, Sequence[int], Sequence[int]]


def index_to_offsets_sizes(
    index: Tuple[slice, ...], global_shape: Sequence[int]
) -> Tuple[List[int], List[int]]:
    """Normalize a ``jax.Shard.index`` (tuple of slices) to offsets/sizes."""
    offsets: List[int] = []
    sizes: List[int] = []
    # 0-d arrays have an empty index.
    for d, dim in enumerate(global_shape):
        sl = index[d] if d < len(index) else slice(None)
        start, stop, step = sl.indices(int(dim))
        if step != 1:
            raise ValueError(f"Strided shard index unsupported: {sl}")
        offsets.append(start)
        sizes.append(stop - start)
    return offsets, sizes


def local_unique_shards(arr: Any) -> List[Tuple[Any, List[int], List[int], int]]:
    """(shard.data, offsets, sizes, replica_id) for each unique local index."""
    out = []
    seen = set()
    shape = arr.shape
    # Visit replica_id==0 copies first so the dedup can never drop the
    # authoritative copy of an index in favor of a local replica (which
    # prepare_write would then skip, silently losing the shard).
    shards = sorted(arr.addressable_shards, key=lambda s: s.replica_id)
    for shard in shards:
        offsets, sizes = index_to_offsets_sizes(shard.index, shape)
        key = tuple(offsets)
        if key in seen:
            continue
        seen.add(key)
        out.append((shard.data, offsets, sizes, shard.replica_id))
    return out


def subdivide(  # spmd-pure
    offsets: List[int],
    sizes: List[int],
    itemsize: int,
    max_bytes: int,
    dim: Optional[int] = None,
) -> List[Tuple[List[int], List[int]]]:
    """Split a shard into <=max_bytes pieces along ``dim`` (default: its
    largest dim). Callers that need byte-contiguous pieces pass ``dim=0``."""
    nbytes = int(np.prod(sizes)) * itemsize if sizes else itemsize
    if nbytes <= max_bytes or not sizes:
        return [(offsets, sizes)]
    if dim is None:
        dim = int(np.argmax(sizes))
    other = int(np.prod(sizes)) // max(sizes[dim], 1) * itemsize
    rows = max(1, max_bytes // max(other, 1))
    pieces = []
    for r0 in range(0, sizes[dim], rows):
        r1 = min(r0 + rows, sizes[dim])
        o = list(offsets)
        s = list(sizes)
        o[dim] = offsets[dim] + r0
        s[dim] = r1 - r0
        pieces.append((o, s))
    return pieces


def overlap(  # spmd-pure
    src_off: Sequence[int],
    src_sz: Sequence[int],
    dst_off: Sequence[int],
    dst_sz: Sequence[int],
) -> Optional[Tuple[Tuple[slice, ...], Tuple[slice, ...]]]:
    """(src_slices, dst_slices) of the intersection, or None."""
    src_slices: List[slice] = []
    dst_slices: List[slice] = []
    for so, ss, do, ds in zip(src_off, src_sz, dst_off, dst_sz):
        lo = max(so, do)
        hi = min(so + ss, do + ds)
        if hi <= lo:
            return None
        src_slices.append(slice(lo - so, hi - so))
        dst_slices.append(slice(lo - do, hi - do))
    return tuple(src_slices), tuple(dst_slices)


def overlap_row_intervals(  # spmd-pure
    shard_off: Sequence[int],
    shard_sz: Sequence[int],
    target_rects: Sequence[Tuple[Sequence[int], Sequence[int]]],
) -> List[Tuple[int, int]]:
    """Union of the shard-relative dim-0 row intervals at least one target
    rectangle overlaps — merged and sorted. The row is the contiguity unit
    of a C-contiguous saved shard: a run of whole rows is exactly one byte
    range, so these intervals are what a minimal-byte reshard fetches
    (column-partial overlaps still cover their whole rows)."""
    ivals: List[Tuple[int, int]] = []
    for dst_off, dst_sz in target_rects:
        ov = overlap(shard_off, shard_sz, dst_off, dst_sz)
        if ov is None:
            continue
        sl = ov[0][0]
        ivals.append((sl.start, sl.stop))
    ivals.sort()
    merged: List[Tuple[int, int]] = []
    for b, e in ivals:
        if merged and b <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], e))
        else:
            merged.append((b, e))
    return merged


def record_grain_for(  # spmd-pure
    digests: Optional[Dict[str, object]], location: str
) -> Optional[int]:
    """The hash-chunk grain of the storage object at ``location`` when its
    sidecar record carries a v2 chunk grid (multi-chunk objects only —
    single-chunk objects keep exact v1 records), else None. Aligning shard
    sub-reads to this grain is what lets ranged reshard reads verify at
    chunk granularity (``VERIFY_READS``) and lets the read cache serve and
    populate chunk-aligned sub-ranges instead of bypassing."""
    if not digests:
        return None
    info = hashing.record_chunk_info(digests.get(location))
    return info[0] if info is not None else None


def shard_read_intervals(  # spmd-pure
    shard: Shard,
    target_rects: Sequence[Tuple[Sequence[int], Sequence[int]]],
    buffer_size_limit_bytes: Optional[int],
    grain: Optional[int] = None,
    merge_gap_bytes: Optional[int] = None,
) -> Optional[List[Tuple[int, int]]]:
    """The byte intervals (relative to the shard's serialized payload) a
    reader must fetch to cover every target overlap — the exact-overlap
    plan for one RAW saved shard:

    1. the overlap row intervals (``overlap_row_intervals``) become byte
       intervals via the shard's row stride;
    2. each interval expands *outward* to hash-chunk boundaries (``grain``,
       in object coordinates — the shard payload may sit at a byte offset
       inside its object) and then to row boundaries, so every fully
       contained chunk is digest-verifiable and cache-addressable;
    3. near-adjacent intervals whose gap is at most ``merge_gap_bytes``
       (default: the ``READ_MERGE_GAP_BYTES`` knob) coalesce — on
       high-latency backends a small discarded gap beats a round trip;
    4. intervals above ``buffer_size_limit_bytes`` split at row boundaries
       (grain-floored when a grain is known), the same one-over-budget
       escape hatch as everywhere: a single row wider than the budget is
       admitted whole.

    Returns ``None`` when the plan is ONE read of the whole payload (full
    coverage, no split required — callers emit the legacy whole-shard
    request so (path, byte_range) shapes stay stable for the collective
    paths), ``[]`` when no target overlaps the shard, else the intervals.
    SPMD-pure: derived from the entry, the target rectangles, knobs, and
    the (globally consistent) digest grain only.
    """
    entry = shard.tensor
    if entry.serializer != Serializer.RAW or not shard.sizes:
        raise ValueError("shard_read_intervals needs a RAW non-scalar shard")
    rows = overlap_row_intervals(shard.offsets, shard.sizes, target_rects)
    if not rows:
        return []
    itemsize = string_to_dtype(entry.dtype).itemsize
    row_bytes = int(np.prod(shard.sizes[1:])) * itemsize
    nbytes = shard.sizes[0] * row_bytes
    base0 = entry.byte_range[0] if entry.byte_range else 0
    if merge_gap_bytes is None:
        merge_gap_bytes = knobs.get_read_merge_gap_bytes()

    def floor_align(pos: int) -> int:
        if grain:
            pos = (base0 + pos) // grain * grain - base0
        return max(0, pos // row_bytes * row_bytes)

    def ceil_align(pos: int) -> int:
        if grain:
            pos = -((base0 + pos) // -grain) * grain - base0
        pos = min(pos, nbytes)
        return min(-(pos // -row_bytes) * row_bytes, nbytes)

    expanded = [
        (floor_align(b * row_bytes), ceil_align(e * row_bytes))
        for b, e in rows
    ]
    merged: List[Tuple[int, int]] = []
    for b, e in expanded:
        if merged and b - merged[-1][1] <= merge_gap_bytes:
            merged[-1] = (merged[-1][0], max(merged[-1][1], e))
        else:
            merged.append((b, e))
    step = None
    if buffer_size_limit_bytes is not None:
        step = max(row_bytes, buffer_size_limit_bytes // row_bytes * row_bytes)
    if (
        len(merged) == 1
        and merged[0] == (0, nbytes)
        and (step is None or nbytes <= step)
    ):
        return None
    if step is None:
        return merged
    split: List[Tuple[int, int]] = []
    for b, e in merged:
        cur = b
        while e - cur > step:
            cut = cur + step
            if grain:
                g = max(0, (base0 + cut) // grain * grain - base0)
                g = g // row_bytes * row_bytes
                if g > cur:
                    cut = g
            split.append((cur, cut))
            cur = cut
        split.append((cur, e))
    return split


class ShardedArrayBufferConsumer(BufferConsumer):
    """Deserializes one saved shard and scatters it into every overlapping
    destination buffer (reference ``ShardedTensorBufferConsumer:288``)."""

    def __init__(
        self,
        entry: ArrayEntry,
        copy_specs: List[Tuple[np.ndarray, Tuple[slice, ...], Tuple[slice, ...]]],
    ) -> None:
        self.entry = entry
        self.copy_specs = copy_specs  # (dst_buffer, src_slices, dst_slices)

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        def work() -> None:
            if is_raw_family(self.entry.serializer):
                decode = (
                    decode_framed_payload
                    if self.entry.frame_bytes
                    else decode_raw_payload
                )
                raw = decode(buf, self.entry.serializer)
                src = array_from_bytes(raw, self.entry.dtype, self.entry.shape)
            else:
                src = pickle.loads(bytes(buf))
            for dst, src_slices, dst_slices in self.copy_specs:
                # 0-d arrays: an empty slice tuple indexes out a scalar, so
                # copy into the array object itself.
                dst_view = dst[dst_slices] if dst_slices else dst
                src_view = src[src_slices] if src_slices else src
                np.copyto(dst_view, src_view, casting="no")

        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, work)
        else:
            work()

    def get_consuming_cost_bytes(self) -> int:
        from .array import entry_cost_bytes

        return entry_cost_bytes(self.entry)


def _shard_piece_deliver(dtype_str: str, piece_shape, copy_specs):
    """Deliver one decoded row-group: view as the piece array and scatter
    into every overlapping destination (the framed analogue of
    :class:`ShardedArrayBufferConsumer`)."""

    def deliver(mv) -> None:
        src = array_from_bytes(mv, dtype_str, piece_shape)
        for dst, src_slices, dst_slices in copy_specs:
            dst_view = dst[dst_slices] if dst_slices else dst
            src_view = src[src_slices] if src_slices else src
            np.copyto(dst_view, src_view, casting="no")

    return deliver


def _framed_shard_reads(
    shard: Shard,
    targets: List[TargetShard],
    frame_table: List[int],
    buffer_size_limit_bytes: int,
) -> List[ReadReq]:
    """Budgeted sub-reads of one FRAMED compressed shard: split into row
    groups <= budget (raw), fetch each group's covering compression frames
    by byte range, decompress only those, scatter the overlaps. A shard
    never enters host memory whole."""
    entry = shard.tensor
    itemsize = string_to_dtype(entry.dtype).itemsize
    F = entry.frame_bytes
    base = entry.byte_range[0] if entry.byte_range else 0
    row_bytes = (
        int(np.prod(shard.sizes[1:])) * itemsize if shard.sizes else itemsize
    )
    shard_raw_total = (
        int(np.prod(shard.sizes)) * itemsize if shard.sizes else itemsize
    )
    # A frame is the decompression quantum: pieces smaller than one frame's
    # row coverage would each re-fetch and re-decode that whole frame (up to
    # frame_bytes/budget amplification with a sub-frame budget), so clamp
    # the effective piece size to >= one frame of rows.
    effective = max(
        buffer_size_limit_bytes,
        ((F + row_bytes - 1) // row_bytes) * row_bytes,
    )
    if not shard.sizes:
        pieces = [(shard.offsets, shard.sizes)]
    else:
        # Exact-overlap: only the row intervals some target actually needs
        # are sliced into frame-covering pieces — a reshard of a framed
        # shard fetches the covering frames of its overlaps, not of the
        # whole shard.
        rects = [(d_off, d_sz) for _dst, d_off, d_sz in targets]
        pieces = []
        for r0, r1 in overlap_row_intervals(shard.offsets, shard.sizes, rects):
            off = list(shard.offsets)
            sz = list(shard.sizes)
            off[0] = shard.offsets[0] + r0
            sz[0] = r1 - r0
            pieces.extend(subdivide(off, sz, itemsize, effective, dim=0))
    prefix = [0]
    for s in frame_table:
        prefix.append(prefix[-1] + int(s))
    reqs: List[ReadReq] = []
    for off, sz in pieces:
        copy_specs = []
        for dst, dst_off, dst_sz in targets:
            ov = overlap(off, sz, dst_off, dst_sz)
            if ov is not None:
                copy_specs.append((dst, ov[0], ov[1]))
        if not copy_specs:
            continue
        a = (off[0] - shard.offsets[0]) * row_bytes if sz else 0
        b = a + (int(np.prod(sz)) * itemsize if sz else itemsize)
        # One group of covering frames per piece (the piece is already
        # budget-sized; frame alignment adds at most 2 partial frames).
        f0 = a // F
        f1 = min(len(frame_table), (b + F - 1) // F)
        cb, ce, grb = prefix[f0], prefix[f1], f0 * F
        reqs.append(
            ReadReq(
                path=entry.location,
                buffer_consumer=FramedSliceConsumer(
                    entry.serializer,
                    group_raw_begin=grb,
                    raw_begin=a,
                    raw_end=b,
                    deliver=_shard_piece_deliver(entry.dtype, list(sz), copy_specs),
                    decoded_raw_bytes=min(f1 * F, shard_raw_total) - grb,
                ),
                byte_range=(base + cb, base + ce),
            )
        )
    return reqs


class ShardedArrayIOPreparer:
    @staticmethod
    def shard_location(logical_path: str, offsets: Sequence[int]) -> str:
        suffix = "_".join(str(o) for o in offsets) or "scalar"
        return f"sharded/{logical_path}.{suffix}"

    @classmethod
    def prepare_write(
        cls,
        logical_path: str,
        arr: Any,  # jax.Array with a non-fully-replicated sharding
        is_async_snapshot: bool = False,
    ) -> Tuple[ShardedArrayEntry, List[WriteReq]]:
        from ..serialization import dtype_to_string, is_raw_serializable

        dtype = np.dtype(arr.dtype)
        max_shard = knobs.get_max_shard_size_bytes()
        shards: List[Shard] = []
        write_reqs: List[WriteReq] = []
        for data, offsets, sizes, replica_id in local_unique_shards(arr):
            if replica_id != 0:
                continue  # another process (or device) owns this copy
            pieces = subdivide(offsets, sizes, dtype.itemsize, max_shard)
            for sub_off, sub_sz in pieces:
                if len(pieces) == 1:
                    # Whole-shard piece (no subdivision): skip the jax
                    # slicing dispatch — `data[full_slices]` still traces a
                    # gather, and at hundreds of params x shards that
                    # dispatch dominated the planning stall (measured 0.17 s
                    # of a 0.29 s prepare_write at 240 sharded entries).
                    piece = data
                else:
                    # Subdivision implies non-empty sizes, so rel is
                    # non-empty here.
                    rel = tuple(
                        slice(o - bo, o - bo + s)
                        for o, bo, s in zip(sub_off, offsets, sub_sz)
                    )
                    piece = data[rel]
                location = cls.shard_location(logical_path, sub_off)
                sub_entry, sub_reqs = ArrayIOPreparer.prepare_write(
                    storage_path=location,
                    arr=piece,
                    replicated=False,
                    is_async_snapshot=is_async_snapshot,
                )
                shards.append(Shard(offsets=sub_off, sizes=sub_sz, tensor=sub_entry))
                write_reqs.extend(sub_reqs)
        entry = ShardedArrayEntry(
            dtype=dtype_to_string(dtype) if is_raw_serializable(dtype) else str(dtype),
            shape=list(arr.shape),
            shards=shards,
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(  # spmd-pure
        entry: ShardedArrayEntry,
        targets: List[TargetShard],
        buffer_size_limit_bytes: Optional[int] = None,
        frame_tables: Optional[Dict[str, List[int]]] = None,
        digests: Optional[Dict[str, object]] = None,
    ) -> List[ReadReq]:
        """Plan reads scattering saved shards into ``targets``.

        **Exact-overlap fetch**: for RAW shards, only the byte ranges the
        targets actually overlap are emitted — the row intervals of the
        overlap union, expanded outward to the sidecar hash-chunk grain
        (``digests`` — so ranged reads verify at chunk granularity under
        ``VERIFY_READS`` and the read cache can serve/populate the
        sub-ranges), coalesced across gaps up to ``READ_MERGE_GAP_BYTES``,
        and split at ``buffer_size_limit_bytes`` so ``read_object`` on an
        operator VM never holds more than ~budget bytes of any one shard
        (``shard_read_intervals``). An N→M reshard therefore fetches ≈ the
        theoretical overlap bytes instead of every overlapping shard whole.
        Non-overlapping saved shards are never fetched; a full-coverage
        unsplit plan stays the legacy single whole-shard request, so the
        collective (bcast/swarm) paths keep their stable (path, byte_range)
        shapes. FRAMED compressed shards (``frame_bytes`` set) fetch the
        compression frames covering their overlap row intervals when their
        ``.ftab`` frame table is supplied. SPMD-pure: a pure function of
        the entry, targets, knobs, and the merged digest sidecars.
        """
        read_reqs: List[ReadReq] = []
        for shard in entry.shards:
            ensure_codec_available(shard.tensor.serializer)
            table = (frame_tables or {}).get(shard.tensor.location)
            if (
                shard.tensor.frame_bytes
                and table is not None
                and buffer_size_limit_bytes is not None
            ):
                read_reqs.extend(
                    _framed_shard_reads(
                        shard, targets, table, buffer_size_limit_bytes
                    )
                )
                continue
            base = tuple(shard.tensor.byte_range) if shard.tensor.byte_range else None
            base0 = base[0] if base else 0

            def whole_shard_req(shard=shard, base=base):
                copy_specs = []
                for dst, dst_off, dst_sz in targets:
                    ov = overlap(shard.offsets, shard.sizes, dst_off, dst_sz)
                    if ov is not None:
                        copy_specs.append((dst, ov[0], ov[1]))
                if not copy_specs:
                    return None
                return ReadReq(
                    path=shard.tensor.location,
                    buffer_consumer=ShardedArrayBufferConsumer(
                        shard.tensor, copy_specs
                    ),
                    byte_range=base,
                )

            if shard.tensor.serializer != Serializer.RAW or not shard.sizes:
                req = whole_shard_req()
                if req is not None:
                    read_reqs.append(req)
                continue
            rects = [(d_off, d_sz) for _dst, d_off, d_sz in targets]
            intervals = shard_read_intervals(
                shard,
                rects,
                buffer_size_limit_bytes,
                grain=record_grain_for(digests, shard.tensor.location),
            )
            if intervals is None:
                req = whole_shard_req()
                if req is not None:
                    read_reqs.append(req)
                continue
            itemsize = string_to_dtype(shard.tensor.dtype).itemsize
            row_bytes = int(np.prod(shard.sizes[1:])) * itemsize
            for b, e in intervals:
                r0, r1 = b // row_bytes, e // row_bytes
                sub_off = list(shard.offsets)
                sub_sz = list(shard.sizes)
                sub_off[0] = shard.offsets[0] + r0
                sub_sz[0] = r1 - r0
                copy_specs = []
                for dst, dst_off, dst_sz in targets:
                    ov = overlap(sub_off, sub_sz, dst_off, dst_sz)
                    if ov is not None:
                        copy_specs.append((dst, ov[0], ov[1]))
                if not copy_specs:
                    continue  # gap-merged rows with no overlap of their own
                sub_entry = ArrayEntry(
                    location=shard.tensor.location,
                    serializer=shard.tensor.serializer,
                    dtype=shard.tensor.dtype,
                    shape=list(sub_sz),
                    replicated=shard.tensor.replicated,
                )
                read_reqs.append(
                    ReadReq(
                        path=shard.tensor.location,
                        buffer_consumer=ShardedArrayBufferConsumer(
                            sub_entry, copy_specs
                        ),
                        byte_range=(base0 + b, base0 + e),
                    )
                )
        return read_reqs


# ---------------------------------------------------------------------------
# Restore-side helpers used by Snapshot: decompose a target sharding into
# host buffers, then assemble a jax.Array from the filled buffers.
# ---------------------------------------------------------------------------

def alloc_target_shards(sharding, global_shape, np_dtype) -> Dict[Tuple[int, ...], Tuple[np.ndarray, List[int], List[int]]]:
    """One host buffer per unique addressable shard index of ``sharding``."""
    out: Dict[Tuple[int, ...], Tuple[np.ndarray, List[int], List[int]]] = {}
    for device in sharding.addressable_devices:
        index = sharding.addressable_devices_indices_map(tuple(global_shape))[device]
        offsets, sizes = index_to_offsets_sizes(index, global_shape)
        key = tuple(offsets)
        if key not in out:
            out[key] = (np.empty(tuple(sizes), dtype=np_dtype), offsets, sizes)
    return out


def process_shard_map(  # spmd-pure
    sharding, global_shape, process_of_device=None
) -> Optional[Dict[int, List[Tuple[List[int], List[int]]]]]:
    """Unique target-shard rectangles per PROCESS of ``sharding``, from the
    GLOBAL device→index map — identical on every rank, which is what lets a
    reshard plan reason about every peer's read set with zero collectives
    (the need-set math of the reshard swarm). ``process_of_device`` is
    injectable for tests that simulate a fleet on one host (defaults to the
    device's ``process_index``). Rectangles are sorted by offsets; returns
    None when the sharding can't produce a global map (exotic sharding
    types — callers fall back to direct reads)."""
    if process_of_device is None:
        def process_of_device(d):
            return getattr(d, "process_index", 0)
    try:
        index_map = sharding.devices_indices_map(
            tuple(int(s) for s in global_shape)
        )
    except Exception:  # pragma: no cover - exotic sharding types
        return None
    out: Dict[int, Dict[Tuple[int, ...], Tuple[List[int], List[int]]]] = {}
    for device, index in index_map.items():
        p = int(process_of_device(device))
        offsets, sizes = index_to_offsets_sizes(index, global_shape)
        out.setdefault(p, {}).setdefault(tuple(offsets), (offsets, sizes))
    return {
        p: [rect for _k, rect in sorted(rects.items())]
        for p, rects in sorted(out.items())
    }


def is_fully_replicated_sharding(sharding, global_shape) -> bool:
    """True when every device of ``sharding`` holds the WHOLE array — the
    ``get_replicate_sharding()`` pattern serving meshes use. Such targets
    make a sharded entry's restore read set identical on every process
    (each reads all shards into one full-extent buffer), which is what lets
    broadcast restore fan one rank's reads out to the fleet. Prefers the
    sharding's own ``is_fully_replicated`` (GSPMD-global: consistent across
    processes); falls back to checking that every *addressable* index spans
    the full extent."""
    flag = getattr(sharding, "is_fully_replicated", None)
    if flag is not None:
        return bool(flag)
    try:
        index_map = sharding.addressable_devices_indices_map(
            tuple(int(s) for s in global_shape)
        )
        for index in index_map.values():
            offsets, sizes = index_to_offsets_sizes(index, global_shape)
            if any(o != 0 for o in offsets) or list(sizes) != [
                int(s) for s in global_shape
            ]:
                return False
        return True
    except Exception:  # pragma: no cover - exotic sharding types
        return False


def assemble_jax_array(sharding, global_shape, buffers: Dict[Tuple[int, ...], Tuple[np.ndarray, List[int], List[int]]]):
    """Build a jax.Array with ``sharding`` from filled host buffers."""
    import jax

    def cb(index):
        offsets, _ = index_to_offsets_sizes(index, global_shape)
        return buffers[tuple(offsets)][0]

    return jax.make_array_from_callback(tuple(int(s) for s in global_shape), sharding, cb)
