"""Fallback pickle preparer for arbitrary objects
(reference ``io_preparers/object.py:34-92``).

Load cannot be in-place for arbitrary objects: the consumer materializes a
fresh object and delivers it through a callback box, which the restore path
splices back into the loaded state dict (reference ``snapshot.py:736-747``).
"""

from __future__ import annotations

import asyncio
import pickle
from concurrent.futures import Executor
from typing import Any, Callable, List, Optional, Tuple

from ..io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from ..manifest import ObjectEntry
from ..serialization import Serializer


class ObjectBufferStager(BufferStager):
    """Objects always stage (pickle into a private buffer) before
    ``async_take`` returns — never ``defer_staging`` — so post-return
    mutations cannot corrupt the snapshot."""

    def __init__(self, obj: Any) -> None:
        self.obj = obj

    def rebind(self, obj: Any) -> None:
        """Swap in the new step's object (prepared-cache hit path); the
        pickle happens at stage time so nothing else is stale."""
        self.obj = obj

    def unbind(self) -> None:
        self.obj = None

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        loop = asyncio.get_running_loop()
        dump = lambda: pickle.dumps(self.obj, protocol=pickle.HIGHEST_PROTOCOL)
        if executor is not None:
            return await loop.run_in_executor(executor, dump)
        return dump()

    def get_staging_cost_bytes(self) -> int:
        # Unknown until pickled; a conservative nominal cost.
        return 1024 * 1024


class ObjectBufferConsumer(BufferConsumer):
    def __init__(self, entry: ObjectEntry) -> None:
        self.entry = entry
        self._callback: Optional[Callable[[Any], None]] = None

    def set_consume_callback(self, callback: Callable[[Any], None]) -> None:
        self._callback = callback

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        obj = pickle.loads(bytes(buf))
        if self._callback is not None:
            self._callback(obj)

    def get_consuming_cost_bytes(self) -> int:
        return 1024 * 1024


class ObjectIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        obj: Any,
        replicated: bool = False,
    ) -> Tuple[ObjectEntry, List[WriteReq]]:
        entry = ObjectEntry(
            location=storage_path,
            serializer=Serializer.PICKLE,
            obj_type=type(obj).__qualname__,
            replicated=replicated,
        )
        return entry, [
            WriteReq(path=storage_path, buffer_stager=ObjectBufferStager(obj))
        ]

    @staticmethod
    def prepare_read(  # spmd-pure
        entry: ObjectEntry,
    ) -> Tuple[List[ReadReq], ObjectBufferConsumer]:
        consumer = ObjectBufferConsumer(entry)
        return [ReadReq(path=entry.location, buffer_consumer=consumer)], consumer
