"""Dim-0 chunking of large arrays (reference ``io_preparers/chunked_tensor.py:34-126``).

Splitting a big array into independent write requests lets the scheduler
pipeline its D2H transfer with storage I/O *within* one array, and lets the
partitioner split a replicated array's write load across processes at chunk
granularity. On TPU the per-chunk slice ``arr[r0:r1]`` is an XLA device op, so
chunk transfers stream out of HBM back-to-back without a full host-side copy
first.

The row-range math (``chunk_row_ranges``) lives in ``array.py`` and is shared
with the streaming stager: each chunk OBJECT produced here is itself streamed
(at the finer ``TORCHSNAPSHOT_TPU_STREAM_CHUNK_BYTES`` grain) when the
scheduler routes it through a storage write stream.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from ..io_types import ReadReq, WriteReq
from ..manifest import ChunkedArrayEntry, Shard
from ..utils import knobs
from .array import ArrayIOPreparer, chunk_row_ranges

__all__ = ["should_chunk", "chunk_row_ranges", "ChunkedArrayIOPreparer"]


def should_chunk(arr: Any) -> bool:
    nbytes = int(np.prod(arr.shape)) * np.dtype(arr.dtype).itemsize if arr.shape else 0
    return (
        len(arr.shape) >= 1
        and arr.shape[0] > 1
        and nbytes > knobs.get_max_chunk_size_bytes()
    )


class ChunkedArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        arr: Any,
        replicated: bool = False,
        is_async_snapshot: bool = False,
    ) -> Tuple[ChunkedArrayEntry, List[WriteReq]]:
        dtype = np.dtype(arr.dtype)
        shape = list(arr.shape)
        ranges = chunk_row_ranges(shape, dtype.itemsize, knobs.get_max_chunk_size_bytes())
        chunks: List[Shard] = []
        write_reqs: List[WriteReq] = []
        for r0, r1 in ranges:
            chunk_path = f"{storage_path}.chunk_{r0}"
            sub_entry, sub_reqs = ArrayIOPreparer.prepare_write(
                storage_path=chunk_path,
                arr=arr[r0:r1],
                replicated=replicated,
                is_async_snapshot=is_async_snapshot,
            )
            offsets = [r0] + [0] * (len(shape) - 1)
            sizes = [r1 - r0] + shape[1:]
            chunks.append(Shard(offsets=offsets, sizes=sizes, tensor=sub_entry))
            write_reqs.extend(sub_reqs)
        entry = ChunkedArrayEntry(
            dtype=chunks[0].tensor.dtype, shape=shape, chunks=chunks, replicated=replicated
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(  # spmd-pure
        entry: ChunkedArrayEntry,
        target: np.ndarray,
        buffer_size_limit_bytes: Optional[int] = None,
        frame_tables: Optional[dict] = None,
    ) -> List[ReadReq]:
        read_reqs: List[ReadReq] = []
        for chunk in entry.chunks:
            r0 = chunk.offsets[0]
            r1 = r0 + chunk.sizes[0]
            view = target[r0:r1]
            read_reqs.extend(
                ArrayIOPreparer.prepare_read(
                    chunk.tensor,
                    view,
                    buffer_size_limit_bytes,
                    frame_table=(frame_tables or {}).get(chunk.tensor.location),
                )
            )
        return read_reqs
