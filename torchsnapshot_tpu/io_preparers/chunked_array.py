"""Dim-0 chunking of large arrays (reference ``io_preparers/chunked_tensor.py:34-126``).

Splitting a big array into independent write requests lets the scheduler
pipeline its D2H transfer with storage I/O *within* one array, and lets the
partitioner split a replicated array's write load across processes at chunk
granularity. On TPU the per-chunk slice ``arr[r0:r1]`` is an XLA device op, so
chunk transfers stream out of HBM back-to-back without a full host-side copy
first.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Tuple

import numpy as np

from ..io_types import ReadReq, WriteReq
from ..manifest import ChunkedArrayEntry, Shard
from ..utils import knobs
from .array import ArrayIOPreparer


def should_chunk(arr: Any) -> bool:
    nbytes = int(np.prod(arr.shape)) * np.dtype(arr.dtype).itemsize if arr.shape else 0
    return (
        len(arr.shape) >= 1
        and arr.shape[0] > 1
        and nbytes > knobs.get_max_chunk_size_bytes()
    )


def chunk_row_ranges(shape, itemsize: int, max_chunk_bytes: int) -> List[Tuple[int, int]]:
    """Row ranges [r0, r1) per chunk, each chunk <= max_chunk_bytes (when a
    single row fits)."""
    dim0 = int(shape[0])
    row_bytes = itemsize * int(np.prod(shape[1:])) if len(shape) > 1 else itemsize
    rows_per_chunk = max(1, max_chunk_bytes // max(row_bytes, 1))
    n_chunks = math.ceil(dim0 / rows_per_chunk)
    # Even spread so the last chunk isn't tiny.
    base = dim0 // n_chunks
    extra = dim0 % n_chunks
    ranges = []
    r0 = 0
    for i in range(n_chunks):
        rows = base + (1 if i < extra else 0)
        ranges.append((r0, r0 + rows))
        r0 += rows
    return ranges


class ChunkedArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        arr: Any,
        replicated: bool = False,
        is_async_snapshot: bool = False,
    ) -> Tuple[ChunkedArrayEntry, List[WriteReq]]:
        dtype = np.dtype(arr.dtype)
        shape = list(arr.shape)
        ranges = chunk_row_ranges(shape, dtype.itemsize, knobs.get_max_chunk_size_bytes())
        chunks: List[Shard] = []
        write_reqs: List[WriteReq] = []
        for r0, r1 in ranges:
            chunk_path = f"{storage_path}.chunk_{r0}"
            sub_entry, sub_reqs = ArrayIOPreparer.prepare_write(
                storage_path=chunk_path,
                arr=arr[r0:r1],
                replicated=replicated,
                is_async_snapshot=is_async_snapshot,
            )
            offsets = [r0] + [0] * (len(shape) - 1)
            sizes = [r1 - r0] + shape[1:]
            chunks.append(Shard(offsets=offsets, sizes=sizes, tensor=sub_entry))
            write_reqs.extend(sub_reqs)
        entry = ChunkedArrayEntry(
            dtype=chunks[0].tensor.dtype, shape=shape, chunks=chunks, replicated=replicated
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ChunkedArrayEntry,
        target: np.ndarray,
        buffer_size_limit_bytes: Optional[int] = None,
        frame_tables: Optional[dict] = None,
    ) -> List[ReadReq]:
        read_reqs: List[ReadReq] = []
        for chunk in entry.chunks:
            r0 = chunk.offsets[0]
            r1 = r0 + chunk.sizes[0]
            view = target[r0:r1]
            read_reqs.extend(
                ArrayIOPreparer.prepare_read(
                    chunk.tensor,
                    view,
                    buffer_size_limit_bytes,
                    frame_table=(frame_tables or {}).get(chunk.tensor.location),
                )
            )
        return read_reqs
