"""Zero-copy array (de)serialization with explicit dtype tables.

TPU-native analogue of the reference's ``serialization.py``
(``/root/reference/torchsnapshot/serialization.py:32-256``). The reference
round-trips ``torch.Tensor`` through the buffer protocol with a special path
for bfloat16 (which numpy can't express); here every accelerator dtype —
including bfloat16, the float8 variants, and int4 — is a first-class numpy
dtype via ``ml_dtypes``, and the uniform zero-copy path is a ``uint8`` view of
the contiguous array (plain ``memoryview(arr)`` raises for ml_dtypes custom
dtypes, so we never use it).

Serializer families:

- ``raw``: little-endian C-contiguous raw bytes. Used for every dtype in
  :data:`SUPPORTED_DTYPES`. Enables ranged reads (a byte range of the
  serialized buffer corresponds to a contiguous region of the flat array).
- ``raw_zstd`` / ``raw_zlib``: the raw byte stream compressed. Opt-in via
  ``TORCHSNAPSHOT_TPU_COMPRESSION`` — on links/stores slower than the
  compressor (tunneled transports, cloud buckets, shared NVMe) the ~1.3-1.5x
  typical ratio on trained bf16/f32 weights directly multiplies effective
  write throughput and shrinks checkpoints. Payloads above
  ``TORCHSNAPSHOT_TPU_COMPRESSION_FRAME_BYTES`` are FRAMED — independent
  frames per fixed raw window, compressed frame sizes in a ``.ftab`` side
  object — so budgeted sub-reads stay byte-range addressable (they fetch and
  decompress only covering frames); smaller payloads are single blobs unless
  slab batching coalesces them into member-framed compressed slabs (one
  frame per member, compressed at staging time). The
  serializer is recorded per entry, so restore auto-detects regardless of
  current knobs, and a compressed and an uncompressed snapshot can coexist.
- ``pickle``: ``pickle`` of arbitrary Python objects. Fallback for
  non-array leaves (reference used ``torch.save``; we have no torch
  dependency on the TPU path).
"""

from __future__ import annotations

import zlib

import numpy as np

try:
    import ml_dtypes
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    ml_dtypes = None


class Serializer:
    RAW = "raw"
    RAW_ZSTD = "raw_zstd"
    RAW_ZLIB = "raw_zlib"
    PICKLE = "pickle"


# Serializers whose decoded payload is the raw little-endian byte stream
# (dtype strings come from the canonical table, shapes are exact).
RAW_FAMILY = (Serializer.RAW, Serializer.RAW_ZSTD, Serializer.RAW_ZLIB)


def is_raw_family(serializer: str) -> bool:
    return serializer in RAW_FAMILY


def raw_serializer_for_codec(codec: str) -> str:
    """Map a compression codec name ('none'|'zstd'|'zlib') to a serializer."""
    if codec == "zstd":
        return Serializer.RAW_ZSTD
    if codec == "zlib":
        return Serializer.RAW_ZLIB
    return Serializer.RAW


def codec_for_raw_serializer(serializer: str) -> str:
    """Inverse of :func:`raw_serializer_for_codec` (single owner of the
    mapping in both directions)."""
    if serializer == Serializer.RAW_ZSTD:
        return "zstd"
    if serializer == Serializer.RAW_ZLIB:
        return "zlib"
    return "none"


def ensure_codec_available(serializer: str) -> None:
    """Fail fast with an actionable error when an entry needs a codec this
    host lacks — called at read *planning* time, so a restore on a box
    without ``zstandard`` raises up front, not mid-pipeline in an executor
    thread (symmetric with the take-side check in ``knobs.get_compression``)."""
    if serializer == Serializer.RAW_ZSTD:
        try:
            import zstandard  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "this snapshot's entries are zstd-compressed; restoring "
                "requires the 'zstandard' package"
            ) from e


def compress_payload(view, serializer: str, level: int) -> bytes:
    """Compress a raw byte view per ``serializer`` (RAW passes through)."""
    if serializer == Serializer.RAW_ZSTD:
        import zstandard

        return zstandard.ZstdCompressor(level=level).compress(view)
    if serializer == Serializer.RAW_ZLIB:
        return zlib.compress(view, level)
    return view


def decode_raw_payload(buf, serializer: str):
    """Undo :func:`compress_payload`: return the raw little-endian bytes.

    Decompressors take buffer-protocol objects directly — no defensive
    ``bytes()`` copy of a possibly-100 MB compressed payload.
    """
    if serializer == Serializer.RAW_ZSTD:
        import zstandard

        return zstandard.ZstdDecompressor().decompress(memoryview(buf))
    if serializer == Serializer.RAW_ZLIB:
        return zlib.decompress(memoryview(buf))
    return buf


def compress_framed(view, serializer: str, level: int, frame_bytes: int):
    """Compress ``view`` as a sequence of independent frames, each covering
    ``frame_bytes`` raw bytes (the last one short). Returns
    ``(payload_bytes, frame_sizes)`` — frames are simply concatenated, so a
    whole-payload read decodes with :func:`decode_framed_payload` and a
    ranged read of frames [i, j) is byte range
    ``[prefix[i], prefix[j])`` of the payload. Deterministic at a fixed
    codec version + level (same property incremental dedup relies on for
    single-blob payloads)."""
    n = memoryview(view).nbytes
    full, tail = divmod(n, frame_bytes)
    member_sizes = [frame_bytes] * full + ([tail] if tail else [])
    if not member_sizes:
        return b"", []
    return compress_member_framed(view, member_sizes, serializer, level)


def compress_member_framed(view, member_sizes, serializer: str, level: int):
    """Compress ``view`` with one independent frame per MEMBER (member i
    covers ``member_sizes[i]`` raw bytes). The slab-batching analogue of
    :func:`compress_framed`: frame boundaries coincide with member
    boundaries, so reading one member fetches + decodes exactly its own
    frames — no shared-frame decode amplification across a slab's members.
    Returns ``(payload_bytes, frame_sizes)``; a whole-payload read decodes
    with :func:`decode_framed_payload` like any framed stream."""
    mv = memoryview(view)
    parts = []
    sizes = []
    begin = 0
    for n in member_sizes:
        frame = compress_payload(mv[begin : begin + n], serializer, level)
        parts.append(frame)
        sizes.append(len(frame))
        begin += n
    assert begin == mv.nbytes, (begin, mv.nbytes)
    return b"".join(parts), sizes


def decode_framed_payload(buf, serializer: str):
    """Decode a concatenation of compression frames back to raw bytes.

    No frame table needed: zstd and zlib streams are self-terminating, so
    concatenated frames decode by reading across frame boundaries.
    """
    if serializer == Serializer.RAW_ZSTD:
        import zstandard

        # stream_reader takes buffer-protocol sources directly — wrapping in
        # BytesIO would copy the whole compressed payload first.
        reader = zstandard.ZstdDecompressor().stream_reader(
            memoryview(buf), read_across_frames=True
        )
        return reader.read()
    if serializer == Serializer.RAW_ZLIB:
        out = []
        rest = memoryview(buf)
        while rest.nbytes:
            d = zlib.decompressobj()
            out.append(d.decompress(rest))
            rest = memoryview(d.unused_data)
        return b"".join(out)
    return buf


def codec_library_versions() -> dict:
    """Versions of the codec libraries in use, recorded in snapshot metadata
    so incremental takes can warn when the base was compressed by a
    different library version (bitstream determinism — hence dedup hit
    rate — only holds within one version)."""
    versions = {"zlib": zlib.ZLIB_RUNTIME_VERSION}
    try:
        import zstandard

        versions["zstd"] = zstandard.__version__
    except ImportError:  # pragma: no cover - zstd optional
        pass
    return versions


def _build_dtype_table():
    table = {
        "bool": np.dtype(np.bool_),
        "uint8": np.dtype(np.uint8),
        "uint16": np.dtype(np.uint16),
        "uint32": np.dtype(np.uint32),
        "uint64": np.dtype(np.uint64),
        "int8": np.dtype(np.int8),
        "int16": np.dtype(np.int16),
        "int32": np.dtype(np.int32),
        "int64": np.dtype(np.int64),
        "float16": np.dtype(np.float16),
        "float32": np.dtype(np.float32),
        "float64": np.dtype(np.float64),
        "complex64": np.dtype(np.complex64),
        "complex128": np.dtype(np.complex128),
    }
    if ml_dtypes is not None:
        for name in (
            "bfloat16",
            "float8_e4m3fn",
            "float8_e5m2",
            "float8_e4m3b11fnuz",
            "float8_e4m3fnuz",
            "float8_e5m2fnuz",
            "int4",
            "uint4",
            "float4_e2m1fn",
            "float8_e3m4",
            "float8_e4m3",
            "float8_e8m0fnu",
        ):
            dt = getattr(ml_dtypes, name, None)
            if dt is not None:
                table[name] = np.dtype(dt)
    return table


# Canonical string <-> numpy dtype tables (reference ``serialization.py:58-96``).
SUPPORTED_DTYPES = _build_dtype_table()
_DTYPE_TO_STRING = {v: k for k, v in SUPPORTED_DTYPES.items()}


def dtype_to_string(dtype) -> str:
    dtype = np.dtype(dtype)
    try:
        return _DTYPE_TO_STRING[dtype]
    except KeyError:
        raise ValueError(f"Unsupported dtype for raw serialization: {dtype}")


def string_to_dtype(s: str) -> np.dtype:
    try:
        return SUPPORTED_DTYPES[s]
    except KeyError:
        raise ValueError(f"Unknown dtype string: {s}")


def is_raw_serializable(dtype) -> bool:
    return np.dtype(dtype) in _DTYPE_TO_STRING


def dtype_itemsize(s: str) -> int:
    return string_to_dtype(s).itemsize


def array_nbytes(shape, dtype_str: str) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype_itemsize(dtype_str)


def array_as_bytes_view(arr: np.ndarray) -> memoryview:
    """Zero-copy little-endian raw-byte view of ``arr``.

    Copies only when the array is non-contiguous or big-endian (single
    owner of the contiguity fix — callers hand the host array straight in).
    Device fetches CAN be non-C-contiguous: ``np.asarray(jax.Array)``
    reflects the device layout, which for e.g. bf16 matrices on TPU may be
    F-order. The view is the RAW staging fast path's terminal product: it
    flows into ``write_stream`` appends / plugin writes / the digest fold
    with no intermediate ``bytes()`` materialization, and it keeps the host
    buffer alive for as long as any consumer holds it.
    """
    arr = np.ascontiguousarray(arr)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    # ml_dtypes custom dtypes reject PEP-3118 export; a uint8 view never
    # does — and ``.data`` already IS the memoryview (no re-wrap copy).
    return arr.view(np.uint8).reshape(-1).data


def array_from_bytes(buf, dtype_str: str, shape) -> np.ndarray:
    """Zero-copy array over ``buf`` (read-only if ``buf`` is)."""
    dtype = string_to_dtype(dtype_str)
    expected = array_nbytes(shape, dtype_str)
    mv = memoryview(buf)
    if mv.nbytes != expected:
        raise ValueError(
            f"Serialized buffer has {mv.nbytes} bytes; "
            f"expected {expected} for shape {tuple(shape)} dtype {dtype_str}"
        )
    return np.frombuffer(mv, dtype=dtype).reshape(shape)
