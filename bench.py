"""Headline benchmark: train-step stall when checkpointing from TPU HBM.

The driver-supplied target (BASELINE.json: "Snapshot.take() stall-time (s) and
GB/s/chip; restore bit-exactness" / north star "<5 s train-step stall with
bit-exact restore") and the reference's own flagship table
(``benchmarks/ddp/README.md``: save wall-time vs torch.save) both measure the
same thing: how long training is blocked by a checkpoint.

This harness saves a transformer-shaped bf16 param pytree living in TPU HBM
with ``Snapshot.async_take()`` and reports:

- headline: the **train-step stall** — how long ``async_take`` blocks before
  training may resume (and donate/replace the params). TPU-native capture
  forks the device buffers instead of staging to host RAM, so the stall is
  planning time, independent of checkpoint size.
- vs_baseline: the stall a reference-style design pays on the *same* hardware
  for the same bytes. The reference's ``async_take`` cannot return until all
  data is captured in host RAM (``snapshot.py:245-314`` + defensive copies,
  ``io_preparers/tensor.py:254-264``), so its stall is bounded below by the
  full device→host transfer — measured here as the background drain (same
  bytes, same link, D2H fully overlapped with writes: a *generous* baseline).
- detail: background drain time, sync-take GB/s, naive single-stream
  (torch.save-style) GB/s on the same hardware, and restore bit-exactness
  checked via random-access ``read_object``.

Prints ONE JSON line on stdout; everything else goes to stderr.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_params(total_gb: float, seed: int = 0):
    """Transformer-shaped bf16 params filling ~total_gb of HBM."""
    import jax
    import jax.numpy as jnp

    d_model, d_ff = 4096, 16384
    layer_bytes = (3 * d_model * d_model + 2 * d_model * d_ff) * 2  # bf16
    n_layers = max(1, round(total_gb * 1e9 / layer_bytes))

    @jax.jit
    def make_layer(key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "attn": jax.random.normal(k1, (d_model, 3 * d_model), jnp.bfloat16),
            "up": jax.random.normal(k2, (d_model, d_ff), jnp.bfloat16),
            "down": jax.random.normal(k3, (d_ff, d_model), jnp.bfloat16),
        }

    params = {}
    key = jax.random.PRNGKey(seed)
    for i in range(n_layers):
        key, sub = jax.random.split(key)
        params[f"layer_{i}"] = make_layer(sub)
    jax.block_until_ready(params)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(params))
    return params, nbytes


def make_link_probe_record(rates, device) -> dict:
    """The link probe's self-description, embedded in the round artifact so
    the regression gate (this round and every later one) can tell whether
    two rounds' ``drain_vs_link`` ratios are comparable AT ALL.

    The r06 miss this exists to prevent: a host change put the probe on a
    CPU backend, where ``np.asarray(device_array)`` measures a ~655 GB/s
    memcpy instead of a ~GB/s device link — the ratio collapsed to 0.0 and
    the gate flagged a phantom regression (the mirror failure, a probe
    suddenly SLOWER, would have masked a real one). A probe is recorded as
    **degenerate** when the device platform is ``cpu`` (there is no
    device link; the copy is host memory bandwidth) or the measured rate
    exceeds any plausible host interconnect (64 GB/s — past PCIe gen5
    x16 territory, so it can only be a memcpy)."""
    import platform as platform_mod

    rate = statistics_median(rates)
    degenerate = device.platform == "cpu" or rate > 64.0
    return {
        "method": "device_get_np_asarray_0.13GB_bf16",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "host": {
            "machine": platform_mod.machine(),
            "cpus": os.cpu_count(),
        },
        "rates_gbps": [round(r, 4) for r in rates],
        "degenerate": degenerate,
    }


def statistics_median(values):
    import statistics

    return statistics.median(values)


def _probe_fingerprint(probe: dict) -> tuple:
    """What must match for two rounds' link measurements to be
    like-for-like: same probe method against the same device kind on the
    same backend. Host CPU details are recorded for humans but don't gate
    (the link is a device property)."""
    return (
        probe.get("method"),
        probe.get("platform"),
        probe.get("device_kind"),
    )


def regression_gate(
    size_gb: float,
    drain_s: float,
    drain_vs_link: float,
    restore_s: float = 0.0,
    stage_hash_s: float = 0.0,
    link_probe: dict = None,
    reshard_wall_s: float = 0.0,
    reshard_ratio: float = 0.0,
) -> dict:
    """Fail-soft regression gate: compare this run's drain wall,
    drain_vs_link, restore wall, AND drain hash time (``stage_hash_s`` —
    the PR-10 headline: chunk-parallel hashing must keep it off the
    critical path) against the BEST prior BENCH_r0*.json taken on the same
    workload (matched by detail.size_gb). Never raises and never aborts the
    bench — the link itself drifts run to run, and the round artifact must
    ALWAYS be written — but a >10% drain/restore-wall regression, a >0.05
    drain_vs_link drop, or a >25%+0.25s hash-time regression is logged
    loudly and recorded in the emitted JSON so the trajectory can't regress
    silently. An EMPTY prior trajectory (first round on a workload, or the
    artifacts were moved) is itself reported loudly as ``no_prior`` rather
    than silently skipping the comparison. Priors that predate a metric
    simply don't constrain it.

    ``drain_vs_link`` is special (the r06 lesson): the ratio is only
    meaningful between LIKE-FOR-LIKE probes. It is compared solely against
    priors whose recorded ``link_probe`` fingerprint (method, platform,
    device kind) matches this round's AND whose probe was not degenerate;
    a degenerate probe this round skips the ratio gate entirely, loudly.
    Priors that predate the probe record can't prove comparability and are
    excluded from the ratio comparison (their drain/restore/hash walls
    still gate). A host change can therefore neither fake a vs-link
    regression nor mask one.

    The reshard surface gates the same way: ``reshard_wall_s`` (the reshard
    matrix's slowest cell) is host-dependent and compares only against
    priors with a matching non-degenerate link-probe fingerprint, while
    ``reshard_ratio`` (origin bytes / theoretical overlap bytes — the
    minimal-byte claim itself) is host-INDEPENDENT and gates against every
    prior that recorded one."""
    try:
        return _regression_gate_impl(
            size_gb, drain_s, drain_vs_link, restore_s, stage_hash_s,
            link_probe or {}, reshard_wall_s, reshard_ratio,
        )
    except Exception as e:  # pragma: no cover - the gate is fail-soft
        log(f"WARNING: bench regression gate errored ({e!r}); skipping")
        return {"status": "error", "priors": 0, "note": repr(e)}


def _regression_gate_impl(
    size_gb: float,
    drain_s: float,
    drain_vs_link: float,
    restore_s: float,
    stage_hash_s: float,
    link_probe: dict,
    reshard_wall_s: float = 0.0,
    reshard_ratio: float = 0.0,
) -> dict:
    import glob

    priors = []
    for path in sorted(glob.glob("BENCH_r0*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
            det = (rec.get("parsed") or {}).get("detail") or {}
            if abs(float(det.get("size_gb", -1.0)) - size_gb) > 0.05:
                continue  # different workload: not comparable
            reshard = det.get("reshard") or {}
            priors.append(
                (
                    path,
                    float(det["background_drain_s"]),
                    float(det.get("drain_vs_link", 0.0)),
                    float((det.get("restore") or {}).get("wall_s", 0.0)),
                    float(
                        (det.get("stage_breakdown_s") or {}).get(
                            "stage_hash_s", 0.0
                        )
                    ),
                    det.get("link_probe") or {},
                    float(reshard.get("reshard_wall_s_max", 0.0)),
                    float(reshard.get("origin_ratio_worst", 0.0)),
                )
            )
        except Exception:
            continue  # unreadable/alien artifact: skip, never fail
    if not priors:
        note = (
            f"no prior BENCH_r0*.json matches this workload "
            f"({size_gb:.2f} GB): nothing to compare against — the round "
            "artifact is still written and seeds the trajectory"
        )
        log(f"WARNING: bench regression gate: {note}")
        return {"status": "no_prior", "priors": 0, "note": note}
    best_drain_s = min(p[1] for p in priors)
    # Like-for-like ratio priors only: same probe fingerprint, both sides
    # non-degenerate. Priors with NO probe record predate the fingerprint
    # and can't prove comparability — excluded from the ratio comparison
    # (recorded below so the exclusion itself is visible).
    link_comparable = [
        p
        for p in priors
        if p[5]
        and not p[5].get("degenerate")
        and _probe_fingerprint(p[5]) == _probe_fingerprint(link_probe)
    ]
    link_excluded = len(priors) - len(link_comparable)
    best_vs_link = (
        max(p[2] for p in link_comparable) if link_comparable else 0.0
    )
    restore_priors = [p[3] for p in priors if p[3] > 0]
    best_restore_s = min(restore_priors) if restore_priors else 0.0
    hash_priors = [p[4] for p in priors if p[4] > 0]
    best_hash_s = min(hash_priors) if hash_priors else 0.0
    problems = []
    link_note = None
    if drain_s > best_drain_s * 1.10:
        problems.append(
            f"drain wall {drain_s:.2f}s is >10% over the best prior "
            f"{best_drain_s:.2f}s"
        )
    if link_probe.get("degenerate"):
        link_note = (
            "this round's link probe is degenerate "
            f"({link_probe.get('platform')} backend at "
            f"{max(link_probe.get('rates_gbps') or [0.0]):.1f} GB/s is a "
            "memcpy, not a device link): drain_vs_link is not gated this "
            "round"
        )
        log(f"WARNING: bench regression gate: {link_note}")
    elif not link_comparable:
        link_note = (
            f"no prior round carries a matching non-degenerate link-probe "
            f"fingerprint ({link_excluded} prior(s) excluded): "
            "drain_vs_link seeds a fresh like-for-like trajectory this "
            "round"
        )
        log(f"WARNING: bench regression gate: {link_note}")
    elif drain_vs_link < best_vs_link - 0.05:
        problems.append(
            f"drain_vs_link {drain_vs_link:.2f} dropped more than 0.05 "
            f"below the best like-for-like prior {best_vs_link:.2f} "
            f"({len(link_comparable)} comparable prior(s))"
        )
    if restore_s > 0 and best_restore_s > 0 and restore_s > best_restore_s * 1.10:
        problems.append(
            f"restore wall {restore_s:.2f}s is >10% over the best prior "
            f"{best_restore_s:.2f}s"
        )
    # Hash wall is small and noisy relative to the drain: gate on a
    # relative AND absolute regression so jitter on a near-zero value
    # can't cry wolf.
    if (
        stage_hash_s > 0
        and best_hash_s > 0
        and stage_hash_s > best_hash_s * 1.25 + 0.25
    ):
        problems.append(
            f"drain stage_hash_s {stage_hash_s:.2f}s is >25% over the best "
            f"prior {best_hash_s:.2f}s — hashing is creeping back onto the "
            "drain's critical path"
        )
    # Reshard wall: host-dependent, like-for-like probe fingerprints only
    # (the same discipline as drain_vs_link — a host change must not fake
    # or mask a reshard regression).
    reshard_wall_priors = [p[6] for p in link_comparable if p[6] > 0]
    best_reshard_wall = min(reshard_wall_priors) if reshard_wall_priors else 0.0
    if (
        reshard_wall_s > 0
        and best_reshard_wall > 0
        and reshard_wall_s > best_reshard_wall * 1.10
    ):
        problems.append(
            f"reshard wall {reshard_wall_s:.2f}s is >10% over the best "
            f"like-for-like prior {best_reshard_wall:.2f}s"
        )
    # Origin-byte ratio: host-independent (pure byte accounting) — gates
    # against every prior that recorded one, plus the absolute 1.1× target.
    ratio_priors = [p[7] for p in priors if p[7] > 0]
    best_ratio = min(ratio_priors) if ratio_priors else 0.0
    if reshard_ratio > 1.1:
        problems.append(
            f"reshard origin-byte ratio {reshard_ratio:.3f}× exceeds the "
            "1.1× theoretical-overlap target — the reshard is over-fetching"
        )
    elif best_ratio > 0 and reshard_ratio > best_ratio + 0.02:
        problems.append(
            f"reshard origin-byte ratio {reshard_ratio:.3f}× regressed from "
            f"the best prior {best_ratio:.3f}×"
        )
    for p in problems:
        log(f"WARNING: bench regression gate: {p}")
    out = {
        "status": "regression" if problems else "ok",
        "priors": len(priors),
        "link_comparable_priors": len(link_comparable),
        "best_prior_drain_s": round(best_drain_s, 2),
        "problems": problems,
    }
    # Metrics with NO prior are reported as ABSENT, not as a 0.0 floor: a
    # zero "best prior" can never flag a regression, so emitting it reads
    # as a fake "ok" (the r07 lesson — best_prior_reshard_wall_s: 0.0 /
    # best_prior_drain_vs_link: 0.0 looked like passing gates that were
    # actually empty). Each absent metric is named in fresh_metrics so the
    # trajectory records WHICH comparisons seeded fresh this round.
    fresh = []
    for key, has_prior, value, digits in (
        ("best_prior_drain_vs_link", bool(link_comparable), best_vs_link, 2),
        ("best_prior_restore_s", bool(restore_priors), best_restore_s, 2),
        ("best_prior_stage_hash_s", bool(hash_priors), best_hash_s, 2),
        (
            "best_prior_reshard_wall_s",
            bool(reshard_wall_priors),
            best_reshard_wall,
            2,
        ),
        ("best_prior_reshard_ratio", bool(ratio_priors), best_ratio, 3),
    ):
        if has_prior:
            out[key] = round(value, digits)
        else:
            fresh.append(key)
    if fresh:
        out["fresh_metrics"] = fresh
        log(
            "WARNING: bench regression gate: no prior round constrains "
            f"{', '.join(fresh)} — these gates seed fresh this round "
            "(reported absent, not 0.0)"
        )
    if link_note:
        out["link_note"] = link_note
    return out


def _chunk_append_hist(snapshot_path: str) -> dict:
    """Per-chunk ``storage.<plugin>.append_s.<bucket>`` histogram summaries
    from a local snapshot's persisted rank-0 telemetry artifact, keyed by
    ``<plugin>.<bucket>``. Empty dict when the snapshot streamed nothing or
    carries no artifact (fail-soft: a bench detail, never a failure)."""
    try:
        with open(
            os.path.join(snapshot_path, ".telemetry", "rank_0.json"),
            encoding="utf-8",
        ) as f:
            metrics = (json.load(f).get("metrics") or {})
    except Exception:
        return {}
    out: dict = {}
    for key, value in metrics.items():
        if not key.startswith("storage.") or ".append_s." not in key:
            continue
        # storage.<plugin>.append_s.<bucket>.<stat>
        head, stat = key.rsplit(".", 1)
        plugin_bucket = head.replace("storage.", "", 1).replace(
            ".append_s", "", 1
        )
        out.setdefault(plugin_bucket, {})[stat] = (
            round(value, 6) if isinstance(value, float) else value
        )
    return out


def measure_naive_save(params_slice, root: str):
    """torch.save-equivalent: blocking device_get of everything, then one
    buffered single-stream pickle write (what the reference benchmarks
    against, ``benchmarks/ddp/README.md:9``). Returns (d2h_s, write_s)."""
    import pickle

    import jax

    t0 = time.perf_counter()
    host = jax.device_get(params_slice)
    d2h_s = time.perf_counter() - t0
    path = os.path.join(root, "naive.pkl")
    t0 = time.perf_counter()
    with open(path, "wb") as f:
        pickle.dump(
            jax.tree.map(lambda a: np.asarray(a).view(np.uint8), host),
            f,
            protocol=pickle.HIGHEST_PROTOCOL,
        )
    write_s = time.perf_counter() - t0
    os.remove(path)
    return d2h_s, write_s


def main() -> None:
    import jax

    from torchsnapshot_tpu import Snapshot, StateDict

    # The headline (async stall) is size-independent; the wall-clock cost is
    # the two background drains over the attached chip's transport, whose
    # bandwidth varies run to run — 1.25 GB keeps the worst case comfortably
    # inside driver timeouts while staying >1 GB of real device state.
    total_gb = float(os.environ.get("BENCH_TOTAL_GB", "1.25"))
    d = jax.devices()[0]
    log(f"device: {d.device_kind} ({d.platform})")

    root = tempfile.mkdtemp(prefix="tss_bench_")
    try:
        # Warmup: absorb one-time costs before any timed run. The native
        # engine builds with a BLOCKING load (the non-blocking plugin path
        # would otherwise leave measured runs on buffered I/O while g++ runs
        # in the background), and the warmup snapshot is an ASYNC take to
        # exercise that path once end-to-end. It cannot pre-compile the
        # batched defensive-copy program for the headline state (the jit is
        # keyed on the full leaf structure + shapes), so the headline
        # separately reports cold vs steady-state stall.
        from torchsnapshot_tpu import native

        native.load_native()
        warm_params, _ = build_params(0.1, seed=99)
        Snapshot.async_take(
            os.path.join(root, "warm"), {"w": StateDict(**warm_params)}
        ).wait()
        del warm_params

        params, nbytes = build_params(total_gb, seed=0)
        gb = nbytes / 1e9
        log(f"built {gb:.2f} GB of bf16 params in HBM")
        sd = StateDict(**params)

        # ---- headline: async_take stall on fresh (uncached) device arrays.
        # Take twice: the first pays the one-time XLA compile of the batched
        # defensive-copy program (keyed on this state's full leaf structure
        # and shapes — the tiny warmup can't cover it); the second is the
        # steady-state stall a training job pays every checkpoint interval.
        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(root, "ckpt_cold"), {"model": sd})
        cold_stall_s = time.perf_counter() - t0
        log(f"async_take stall (cold, incl. XLA compile): {cold_stall_s:.3f}s")
        pending.wait()
        shutil.rmtree(os.path.join(root, "ckpt_cold"), ignore_errors=True)
        # Link-rate probes bracketing the drain: a bare device_get of a
        # fresh ~0.13 GB array, the same transfer the drain's staging must
        # saturate. The drain is judged against the link measured AROUND it
        # (the tunnel drifts minute-to-minute; the A/B section's rates come
        # minutes later).
        import jax.numpy as jnp

        def probe_link(seed: int) -> float:
            a = jax.random.normal(
                jax.random.PRNGKey(7000 + seed), (4096, 16384), jnp.bfloat16
            )
            jax.block_until_ready(a)
            t0 = time.perf_counter()
            h = np.asarray(a)
            return h.nbytes / 1e9 / (time.perf_counter() - t0)

        link_before = probe_link(0)
        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(root, "ckpt_async"), {"model": sd})
        stall_s = time.perf_counter() - t0
        log(f"async_take stall (steady-state): {stall_s:.3f}s (training may resume/donate here)")
        from torchsnapshot_tpu import snapshot as snapshot_mod

        stall_phases = {
            k: round(v, 4) for k, v in snapshot_mod.LAST_TAKE_PHASES.items()
        }
        log(f"stall decomposition: {stall_phases}")
        t0 = time.perf_counter()
        pending.wait()
        drain_s = time.perf_counter() - t0
        drain_stats = {k: round(v, 2) for k, v in pending.drain_stats.items()}
        link_after = probe_link(1)
        import statistics

        link_gbps = statistics.median([link_before, link_after])
        drain_gbps = gb / drain_s
        drain_vs_link = drain_gbps / link_gbps
        # Probe self-description (method + device + host fingerprint +
        # degeneracy): rounds are only vs-link-comparable when these match.
        link_probe = make_link_probe_record([link_before, link_after], d)
        if link_probe["degenerate"]:
            log(
                f"WARNING: link probe is degenerate on this host "
                f"({d.platform} backend, {link_gbps:.1f} GB/s is host "
                "memory bandwidth, not a device link): drain_vs_link is "
                "recorded but not meaningful this round"
            )
        log(f"background drain (D2H + storage I/O): {drain_s:.2f}s {drain_stats}")
        # stage_busy decomposed (the PR-6 attribution): where staging time
        # actually went. With parallel lanes the sub-streams overlap, so
        # their sum can exceed stage_busy — each is that sub-stream's own
        # busy time.
        stage_breakdown = {
            k: drain_stats.get(k, 0.0)
            for k in ("stage_d2h_s", "stage_serialize_s", "stage_hash_s")
        }
        log(
            f"stage breakdown: d2h {stage_breakdown['stage_d2h_s']:.2f}s, "
            f"serialize {stage_breakdown['stage_serialize_s']:.2f}s, "
            f"hash {stage_breakdown['stage_hash_s']:.2f}s "
            f"(stage_busy {drain_stats.get('stage_busy_s', 0.0):.2f}s)"
        )
        log(
            f"drain rate {drain_gbps:.4f} GB/s vs link {link_gbps:.4f} GB/s "
            f"(probes {link_before:.4f}/{link_after:.4f}) -> "
            f"drain_vs_link {drain_vs_link:.2f}"
        )
        # The drain is a D2H-bound stream on this link; its wall must track
        # bytes/link-rate. Flag (don't abort: the probes themselves ride a
        # drifting tunnel) when it runs >15% under the bracketing link rate
        # — unless the probe is degenerate, where the ratio means nothing.
        if drain_vs_link < 0.85 and not link_probe["degenerate"]:
            log(
                f"WARNING: background drain ran at {drain_vs_link:.2f}x of "
                "the link rate measured around it (target >= 0.85): the "
                "staging stream is not saturating the transfer"
            )

        # ---- steady-state: repeated takes of the SAME tree through the
        # prepared-state cache (prepare_cache.py) under donation-style
        # capture. This is the training-job regime — take(job=, step=)
        # every interval on an unchanged structure — and the tentpole
        # surface: warm stalls are re-binds (no stager construction, no
        # partition, no defensive fork), reported as p50/max SEPARATE from
        # the cold numbers above. Target: warm stall <= 0.1s.
        from torchsnapshot_tpu import prepare_cache as _prepare_cache
        from torchsnapshot_tpu.parallel.coordinator import (
            get_coordinator as _get_coordinator,
        )
        from torchsnapshot_tpu.utils import knobs as _knobs

        steady_steps = int(os.environ.get("BENCH_STEADY_STEPS", "4"))
        steady_bucket = os.path.join(root, "steady_bucket")
        os.makedirs(steady_bucket, exist_ok=True)
        steady_stalls = []
        steady_phases = {}
        with _knobs.override_async_capture("donate"), _knobs.override_catalog(
            False
        ):
            # donate: the caller-promise mode — this bench does not donate
            # or delete `sd`'s arrays while a take is pending, which is
            # exactly the contract TORCHSNAPSHOT_TPU_ASYNC_CAPTURE=donate
            # names. catalog off: auto-base would turn steps 1+ into
            # INCREMENTAL takes (base=prev step — here deleted as soon as
            # it completes), and incremental takes bypass the prepared
            # cache by design; this leg isolates the warm FULL-take stall.
            for step in range(steady_steps):
                t0 = time.perf_counter()
                pend = Snapshot.async_take(
                    os.path.join(steady_bucket, f"step_{step:05d}"),
                    {"model": sd},
                    job="bench-steady",
                    step=step,
                )
                steady_stalls.append(time.perf_counter() - t0)
                steady_phases = {
                    k: round(v, 4)
                    for k, v in snapshot_mod.LAST_TAKE_PHASES.items()
                }
                pend.wait()
                shutil.rmtree(
                    os.path.join(steady_bucket, f"step_{step:05d}"),
                    ignore_errors=True,
                )
        # Step 0 builds + stores the prepared state (a miss: construction
        # already amortized into this take's pipeline); steps 1+ are warm.
        warm = steady_stalls[1:] if len(steady_stalls) > 1 else steady_stalls
        steady_record = {
            "steps": steady_steps,
            "stall_cold_s": round(steady_stalls[0], 4),
            "warm_stall_p50_s": round(statistics_median(warm), 4),
            "warm_stall_max_s": round(max(warm), 4),
            "warm_stall_all_s": [round(s, 4) for s in warm],
            "target_warm_stall_s": 0.1,
            "stall_phases_s": steady_phases,
            "cache": _prepare_cache.stats(_get_coordinator()),
        }
        steady_record["within_target"] = bool(
            steady_record["warm_stall_p50_s"] <= 0.1
        )
        log(f"steady-state takes (prepared cache + donate capture): {steady_record}")
        if not steady_record["within_target"]:
            log(
                "WARNING: warm steady-state stall p50 "
                f"{steady_record['warm_stall_p50_s']:.3f}s exceeds the "
                "0.1s target — the prepared-state cache is not keeping "
                "re-prepare off the critical path on this host"
            )
        shutil.rmtree(steady_bucket, ignore_errors=True)

        # ---- detail: sync take vs naive torch.save-style, INTERLEAVED A/B
        # with >=3 reps each on disjoint fresh device arrays, reported as
        # medians + spread (VERDICT round 2, item 2: a single rep per side
        # on a link whose bandwidth drifts minute-to-minute flipped the
        # sign between rounds). Fresh arrays per rep: jax caches the host
        # copy after the first device_get (``jax.Array._npy_value``), so any
        # reuse hands one side a free D2H.
        ab_reps = int(os.environ.get("BENCH_AB_REPS", "3"))
        # Several mid-size arrays per slice, not one huge one: a real
        # checkpoint holds many tensors, and the pipeline's edge over the
        # naive path is overlapping multiple D2H streams with writes — a
        # 2-array slice would cap its concurrency at 2 and measure nothing.
        arrs_per_slice = 6

        def build_ab_slice(seed: int):
            ks = jax.random.split(jax.random.PRNGKey(1000 + seed), arrs_per_slice)
            slice_ = {
                f"a{j}": jax.random.normal(ks[j], (2048, 8192), jax.numpy.bfloat16)
                for j in range(arrs_per_slice)
            }
            jax.block_until_ready(slice_)
            return slice_

        naive_rates, naive_d2h_rates, sync_rates = [], [], []

        def run_naive(rep: int) -> None:
            naive_sub = build_ab_slice(2 * rep)
            sub_gb = sum(
                x.nbytes for x in jax.tree_util.tree_leaves(naive_sub)
            ) / 1e9
            d2h_s, write_s = measure_naive_save(naive_sub, root)
            naive_rates.append(sub_gb / (d2h_s + write_s))
            naive_d2h_rates.append(sub_gb / d2h_s)

        sync_drains = []

        def run_sync(rep: int) -> None:
            sync_sub = build_ab_slice(2 * rep + 1)
            sub_gb = sum(
                x.nbytes for x in jax.tree_util.tree_leaves(sync_sub)
            ) / 1e9
            t0 = time.perf_counter()
            Snapshot.take(
                os.path.join(root, f"ckpt_sync_{rep}"),
                {"model": StateDict(**sync_sub)},
            )
            sync_rates.append(sub_gb / (time.perf_counter() - t0))
            # Same stream decomposition the async drain reports, so a slow
            # sync rep is attributable (D2H+serialize vs storage writes)
            # instead of a bare wall-clock number (VERDICT round 4, item 1).
            sync_drains.append(
                {
                    k: round(v, 2)
                    for k, v in snapshot_mod.LAST_SYNC_DRAIN_STATS.items()
                }
            )
            shutil.rmtree(os.path.join(root, f"ckpt_sync_{rep}"), ignore_errors=True)

        for rep in range(ab_reps):
            # Alternate which side goes first so a monotonic bandwidth drift
            # in the tunnel biases neither side.
            first, second = (run_naive, run_sync) if rep % 2 == 0 else (run_sync, run_naive)
            first(rep)
            second(rep)
            log(
                f"A/B rep {rep}: naive {naive_rates[-1]:.4f} GB/s "
                f"(D2H {naive_d2h_rates[-1]:.4f}), sync take {sync_rates[-1]:.4f} GB/s "
                f"(drain {sync_drains[-1]})"
            )

        naive_gbps = statistics.median(naive_rates)
        sync_gbps = statistics.median(sync_rates)
        log(
            f"A/B medians over {ab_reps} interleaved reps: naive "
            f"{naive_gbps:.4f} GB/s (spread {min(naive_rates):.4f}-"
            f"{max(naive_rates):.4f}), sync take {sync_gbps:.4f} GB/s "
            f"(spread {min(sync_rates):.4f}-{max(sync_rates):.4f})"
        )

        # Reference-design stall lower bound on the same hardware: its
        # async_take cannot return before all bytes are captured in host RAM,
        # i.e. at best one full device->host transfer — extrapolated from the
        # median measured D2H rate (NOT from the drain, which also contains
        # storage I/O and would overstate the baseline when disk is the
        # bottleneck).
        ref_equiv_stall_s = gb / statistics.median(naive_d2h_rates)

        # ---- streaming on/off A/B: the intra-request overlap win. Same
        # interleaved-reps protocol as the naive/sync A/B (fresh device
        # arrays per rep, alternating order, link probes bracketing each
        # drain) so the trajectory records drain_vs_link for BOTH paths.
        from torchsnapshot_tpu.utils import knobs as _knobs

        stream_reps = int(os.environ.get("BENCH_STREAM_AB_REPS", "2"))
        stream_gb = float(os.environ.get("BENCH_STREAM_AB_GB", "0.5"))
        # Two big dim-0-chunkable arrays: above the streaming threshold
        # (2 x TORCHSNAPSHOT_TPU_STREAM_CHUNK_BYTES), so the on-side drains
        # them as chunk streams while the off-side stages whole.
        stream_rows = max(4, int(stream_gb * 1e9 / 2 / (16384 * 2)))

        def build_stream_slice(seed: int):
            import jax.numpy as jnp

            ks = jax.random.split(jax.random.PRNGKey(3000 + seed), 2)
            s = {
                f"b{j}": jax.random.normal(
                    ks[j], (stream_rows, 16384), jnp.bfloat16
                )
                for j in range(2)
            }
            jax.block_until_ready(s)
            return s

        stream_sides = {"on": [], "off": []}

        def run_stream_rep(rep: int, enabled: bool) -> None:
            label = "on" if enabled else "off"
            sub = build_stream_slice(2 * rep + (0 if enabled else 1))
            sub_gb = sum(
                x.nbytes for x in jax.tree_util.tree_leaves(sub)
            ) / 1e9
            link0 = probe_link(100 + 10 * rep + (0 if enabled else 5))
            with _knobs.override_stream_writes(enabled):
                pend = Snapshot.async_take(
                    os.path.join(root, f"ckpt_stream_{label}_{rep}"),
                    {"model": StateDict(**sub)},
                )
                t0 = time.perf_counter()
                pend.wait()
                rep_drain_s = time.perf_counter() - t0
            link1 = probe_link(300 + 10 * rep + (0 if enabled else 5))
            link = statistics.median([link0, link1])
            ds = pend.drain_stats
            shorter = min(ds.get("stage_busy_s", 0.0), ds.get("io_busy_s", 0.0))
            rate = sub_gb / max(rep_drain_s, 1e-9)
            stream_sides[label].append(
                {
                    "drain_s": round(rep_drain_s, 2),
                    "drain_gbps": round(rate, 4),
                    "link_gbps": round(link, 4),
                    "drain_vs_link": round(rate / link, 2),
                    "overlap_s": round(ds.get("overlap_s", 0.0), 2),
                    "overlap_frac_of_shorter": round(
                        ds.get("overlap_s", 0.0) / shorter, 2
                    )
                    if shorter > 0
                    else 1.0,
                    "stage_busy_s": round(ds.get("stage_busy_s", 0.0), 2),
                    "io_busy_s": round(ds.get("io_busy_s", 0.0), 2),
                    # Per-chunk append-latency histogram (per plugin, size
                    # bucketed) from the persisted artifact: attributes an
                    # inversion to per-chunk overhead vs grain vs the disk.
                    "chunk_append_s": _chunk_append_hist(
                        os.path.join(root, f"ckpt_stream_{label}_{rep}")
                    ),
                }
            )
            log(
                f"stream A/B rep {rep} [{label}]: {sub_gb:.2f} GB drained in "
                f"{rep_drain_s:.2f}s -> {stream_sides[label][-1]}"
            )
            shutil.rmtree(
                os.path.join(root, f"ckpt_stream_{label}_{rep}"),
                ignore_errors=True,
            )

        for rep in range(stream_reps):
            # Alternate which side goes first (same drift hygiene as above).
            order = (True, False) if rep % 2 == 0 else (False, True)
            run_stream_rep(rep, order[0])
            run_stream_rep(rep, order[1])

        def _median_of(label: str, key: str) -> float:
            return statistics.median(r[key] for r in stream_sides[label])

        stream_ab = {
            "reps": stream_reps,
            "size_gb": round(stream_gb, 2),
            "on": {
                k: _median_of("on", k)
                for k in (
                    "drain_gbps",
                    "drain_vs_link",
                    "overlap_s",
                    "overlap_frac_of_shorter",
                )
            },
            "off": {
                k: _median_of("off", k)
                for k in (
                    "drain_gbps",
                    "drain_vs_link",
                    "overlap_s",
                    "overlap_frac_of_shorter",
                )
            },
            "all": stream_sides,
        }
        # Merge the on-side per-rep chunk histograms: counts/sums add,
        # extremes take min/max, percentiles keep the worst rep
        # (conservative — bucket-exact merging isn't worth carrying here).
        chunk_merged: dict = {}
        for rep_rec in stream_sides["on"]:
            for pb, stats_d in (rep_rec.get("chunk_append_s") or {}).items():
                m = chunk_merged.setdefault(pb, {})
                for stat, v in stats_d.items():
                    if stat in ("count", "sum"):
                        m[stat] = m.get(stat, 0) + v
                    elif stat == "min":
                        m[stat] = min(m.get(stat, v), v)
                    else:
                        m[stat] = max(m.get(stat, v), v)
        for m in chunk_merged.values():
            if m.get("count"):
                m["mean"] = round(m.get("sum", 0.0) / m["count"], 6)
        stream_ab["chunk_append_s"] = chunk_merged
        log(f"stream A/B medians: on={stream_ab['on']} off={stream_ab['off']}")
        if chunk_merged:
            log(f"stream A/B per-chunk append latency (on side): {chunk_merged}")
        # Fail-soft inversion flag: streaming exists to BEAT the whole-
        # buffer path; when ON underperforms OFF by >10% on this host (the
        # r07 artifact measured 0.21 vs 0.36 GB/s and buried it in
        # `detail`), say so loudly and mark the artifact so the trajectory
        # records the inversion as a first-class signal instead of a
        # footnote.
        ab_on, ab_off = stream_ab["on"]["drain_gbps"], stream_ab["off"]["drain_gbps"]
        stream_ab["stream_ab_inverted"] = bool(
            ab_off > 0 and ab_on < 0.9 * ab_off
        )
        if stream_ab["stream_ab_inverted"]:
            log(
                "WARNING: stream A/B INVERTED on this host: streaming ON "
                f"drained at {ab_on:.3f} GB/s vs OFF at {ab_off:.3f} GB/s "
                "(>10% slower) — chunk streaming is hurting, not helping; "
                "suspect chunk size vs this host's per-append overhead "
                "(TORCHSNAPSHOT_TPU_STREAM_CHUNK_BYTES) before trusting "
                "the streamed path's defaults here"
            )

        # ---- STREAM_WRITES=auto leg + regression gate. The A/B reps above
        # fed the per-plugin scorecard through the live pipeline (streamed
        # appends and whole-buffer writes are measured unconditionally), so
        # the shipped `auto` default now has credible evidence on this
        # host. Run one auto-mode drain, record the decision the selector
        # made, and FAIL the bench if auto picked the measured losing side
        # — the r07 inversion shipped precisely because the default was a
        # blind boolean nobody compared against the measurement.
        from torchsnapshot_tpu import stream_select as _stream_select

        auto_sub = build_stream_slice(9000)
        auto_gb = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(auto_sub)
        ) / 1e9
        with _knobs.override_stream_writes_mode("auto"):
            pend = Snapshot.async_take(
                os.path.join(root, "ckpt_stream_auto"), {"model": StateDict(**auto_sub)}
            )
            t0 = time.perf_counter()
            pend.wait()
            auto_drain_s = time.perf_counter() - t0
        del auto_sub
        shutil.rmtree(os.path.join(root, "ckpt_stream_auto"), ignore_errors=True)
        auto_decision = _stream_select.last_decision()
        auto_gbps = auto_gb / max(auto_drain_s, 1e-9)
        # The losing side exists only when the measured A/B separated the
        # sides by >10% (the same tolerance as the inversion flag); inside
        # the band either pick is fine.
        losing_side = None
        if ab_off > 0 and ab_on < 0.9 * ab_off:
            losing_side = "on"
        elif ab_on > 0 and ab_off < 0.9 * ab_on:
            losing_side = "off"
        picked = (
            "on" if auto_decision and auto_decision.get("enabled") else "off"
        )
        picked_losing = bool(
            losing_side is not None
            and auto_decision is not None
            and auto_decision.get("mode") == "auto"
            and picked == losing_side
        )
        stream_ab["auto"] = {
            "decision": auto_decision,
            "scorecard": _stream_select.scorecard(
                auto_decision["plugin"] if auto_decision else "fs"
            ),
            "drain_gbps": round(auto_gbps, 4),
            "losing_side": losing_side,
            "picked": picked,
            "picked_losing_side": picked_losing,
        }
        log(f"stream auto-select: {stream_ab['auto']}")
        if picked_losing:
            raise SystemExit(
                f"stream auto-select REGRESSION: auto picked '{picked}' but "
                f"the measured A/B says '{losing_side}' is the losing side "
                f"on this host (on {ab_on:.3f} vs off {ab_off:.3f} GB/s)"
            )

        # ---- persisted-telemetry summary: the async checkpoint carries its
        # own attribution (.telemetry/rank_0.json written by the drain);
        # embed the aggregated view so the perf trajectory's numbers come
        # with phase/drain/byte attribution from the snapshot itself.
        telemetry_summary = None
        try:
            from torchsnapshot_tpu.telemetry import aggregate as tagg

            ws, arts, art_problems = tagg.read_snapshot_artifacts(
                os.path.join(root, "ckpt_async")
            )
            if arts:
                agg = tagg.aggregate(arts, world_size=ws)
                rank0 = agg["per_rank"][0]
                telemetry_summary = {
                    "phases_s": {
                        k: round(v["max"], 4) for k, v in agg["phases_s"].items()
                    },
                    "drain_stats_s": {
                        k: round(rank0[k], 2)
                        for k in (
                            "wall_s",
                            "stage_busy_s",
                            "io_busy_s",
                            "overlap_s",
                            "idle_s",
                        )
                    },
                    "bytes_written": agg["totals"]["bytes_written"],
                    "storage_bytes": agg["storage_bytes"],
                    "spans_dropped": agg["spans_dropped"],
                    "artifact_problems": {
                        str(r): p for r, p in sorted(art_problems.items())
                    },
                }
                log(f"telemetry summary (from persisted artifacts): {telemetry_summary}")
        except Exception as e:  # diagnostics must never fail the bench
            log(f"WARNING: telemetry artifact aggregation failed: {e!r}")

        # ---- restore bit-exactness via random access into the async ckpt
        snap = Snapshot(os.path.join(root, "ckpt_async"))
        probe = list(params)[-1]
        ok = all(
            np.array_equal(
                np.asarray(snap.read_object(f"0/model/{probe}/{k}")).view(np.uint8),
                np.asarray(params[probe][k]).view(np.uint8),
            )
            for k in params[probe]
        )
        log(f"restore bit-exact: {ok}")
        if not ok:
            raise SystemExit("restore mismatch")

        # ---- restore wall (serving-side regression surface): a full
        # cold restore of the checkpoint into fresh host targets, with the
        # read-pipeline stats the restore path now reports
        # (snapshot.LAST_RESTORE_STATS).
        restore_sd = StateDict()
        t0 = time.perf_counter()
        Snapshot(os.path.join(root, "ckpt_async")).restore({"model": restore_sd})
        restore_s = time.perf_counter() - t0
        del restore_sd
        restore_record = {
            "wall_s": round(restore_s, 3),
            "gbps": round(gb / max(restore_s, 1e-9), 4),
        }
        for k in ("bytes_read", "read_wall_s", "requests"):
            v = snapshot_mod.LAST_RESTORE_STATS.get(k)
            if v is not None:
                restore_record[k] = round(float(v), 4)
        log(f"full restore: {restore_record}")

        # ---- flight-recorder overhead A/B + job step timeline. The
        # recorder is always-on by default, so its cost must be provably
        # in the noise: interleaved async takes with the recorder on vs
        # off (same protocol as the stream A/B), compared on the drain
        # wall median — acceptance is <=1% overhead. Then a short job-mode
        # take sequence exercises the per-step catalog rollup end to end
        # and runs the health detectors over it: a clean run on a healthy
        # host must flag NOTHING (the zero-false-positive surface the
        # continuous bench asserts at scale). Both fail-soft: diagnostics
        # never sink the drain trajectory.
        recorder_ab = None
        job_timeline = None
        try:
            from torchsnapshot_tpu import catalog as _catalog
            from torchsnapshot_tpu.telemetry import health as _health
            from torchsnapshot_tpu.telemetry import recorder as _recorder
            from torchsnapshot_tpu.telemetry import steprecord as _steprecord

            rec_reps = int(os.environ.get("BENCH_RECORDER_AB_REPS", "5"))
            rec_walls = {"on": [], "off": []}

            def run_recorder_rep(rep: int, enabled: bool) -> None:
                label = "on" if enabled else "off"
                sub = build_stream_slice(7000 + 2 * rep + (0 if enabled else 1))
                with _knobs.override_recorder(enabled):
                    _recorder.reset()  # re-arm the singleton under the knob
                    pend = Snapshot.async_take(
                        os.path.join(root, f"ckpt_rec_{label}_{rep}"),
                        {"model": StateDict(**sub)},
                    )
                    t0 = time.perf_counter()
                    pend.wait()
                    rec_walls[label].append(time.perf_counter() - t0)
                shutil.rmtree(
                    os.path.join(root, f"ckpt_rec_{label}_{rep}"),
                    ignore_errors=True,
                )

            for rep in range(rec_reps):
                order = (True, False) if rep % 2 == 0 else (False, True)
                run_recorder_rep(rep, order[0])
                run_recorder_rep(rep, order[1])
            _recorder.reset()  # back to the ambient knob state
            on_med = statistics.median(rec_walls["on"])
            off_med = statistics.median(rec_walls["off"])
            overhead = (on_med - off_med) / off_med if off_med > 0 else 0.0
            recorder_ab = {
                "reps": rec_reps,
                "on_drain_wall_s": round(on_med, 4),
                "off_drain_wall_s": round(off_med, 4),
                "overhead_frac": round(overhead, 4),
                "within_budget": bool(overhead <= 0.01),
                "on_all": [round(w, 4) for w in rec_walls["on"]],
                "off_all": [round(w, 4) for w in rec_walls["off"]],
            }
            log(f"recorder A/B: {recorder_ab}")
            if not recorder_ab["within_budget"]:
                log(
                    "WARNING: flight-recorder drain overhead "
                    f"{overhead * 100:.2f}% exceeds the 1% always-on "
                    "budget on this host"
                )

            jt_steps = int(os.environ.get("BENCH_JOB_TIMELINE_STEPS", "8"))
            jt_bucket = os.path.join(root, "job_bucket")
            os.makedirs(jt_bucket, exist_ok=True)
            rngj = np.random.default_rng(7)
            jt_frozen = {
                f"f{i}": rngj.standard_normal(1 << 20).astype(np.float32)
                for i in range(2)
            }
            jt_adapt = {"lora": rngj.standard_normal(1 << 16).astype(np.float32)}
            for step in range(jt_steps):
                jt_adapt["lora"] = jt_adapt["lora"] + 1.0
                Snapshot.take(
                    os.path.join(jt_bucket, f"step_{step:05d}"),
                    {"m": StateDict(**jt_frozen, **jt_adapt)},
                    job="bench-job",
                    step=step,
                    max_chain_len=4,
                )
            with _catalog.Catalog(jt_bucket) as cat:
                jt_series = cat.load_step_telemetry(job="bench-job")
            jt_anomalies = _health.detect_anomalies(jt_series)
            job_timeline = {
                "steps": jt_steps,
                "steps_recorded": len(jt_series),
                "summary": _steprecord.summarize_series(jt_series),
                "anomalies": jt_anomalies,
                "timeline": _health.render_timeline(jt_series, jt_anomalies),
            }
            for line in job_timeline["timeline"]:
                log(f"  {line}")
            if jt_anomalies:
                log(
                    "WARNING: health detectors flagged a clean job-mode "
                    f"run: {sorted({a['kind'] for a in jt_anomalies})}"
                )
            shutil.rmtree(jt_bucket, ignore_errors=True)
        except Exception as e:  # fail-soft by design
            log(
                "WARNING: recorder A/B / job-timeline leg failed "
                f"({e!r}); recorded as absent"
            )

        # ---- fleet-beacon overhead A/B: same interleaved protocol as the
        # recorder A/B, with the fleet telemetry bus forced on vs off
        # (world=1 over the in-process store, so "auto" would resolve
        # off — force "1" to actually publish). The beacon path is
        # rate-limited store writes off the drain's critical path, so
        # acceptance is the same <=1% drain-wall budget. Fail-soft.
        beacon_ab = None
        try:
            from torchsnapshot_tpu.telemetry import fleet as _fleet

            bcn_reps = int(os.environ.get("BENCH_BEACON_AB_REPS", "5"))
            bcn_walls = {"on": [], "off": []}

            def run_beacon_rep(rep: int, enabled: bool) -> None:
                label = "on" if enabled else "off"
                sub = build_stream_slice(9000 + 2 * rep + (0 if enabled else 1))
                with _knobs.override_fleet_telemetry(
                    "1" if enabled else "0"
                ), _knobs.override_fleet_beacon_s(0.1):
                    _fleet.reset()  # re-arm the singleton under the knob
                    pend = Snapshot.async_take(
                        os.path.join(root, f"ckpt_bcn_{label}_{rep}"),
                        {"model": StateDict(**sub)},
                    )
                    t0 = time.perf_counter()
                    pend.wait()
                    bcn_walls[label].append(time.perf_counter() - t0)
                shutil.rmtree(
                    os.path.join(root, f"ckpt_bcn_{label}_{rep}"),
                    ignore_errors=True,
                )

            for rep in range(bcn_reps):
                order = (True, False) if rep % 2 == 0 else (False, True)
                run_beacon_rep(rep, order[0])
                run_beacon_rep(rep, order[1])
            _fleet.reset()  # back to the ambient knob state
            bcn_on = statistics.median(bcn_walls["on"])
            bcn_off = statistics.median(bcn_walls["off"])
            bcn_overhead = (
                (bcn_on - bcn_off) / bcn_off if bcn_off > 0 else 0.0
            )
            beacon_ab = {
                "reps": bcn_reps,
                "on_drain_wall_s": round(bcn_on, 4),
                "off_drain_wall_s": round(bcn_off, 4),
                "overhead_frac": round(bcn_overhead, 4),
                "within_budget": bool(bcn_overhead <= 0.01),
                "on_all": [round(w, 4) for w in bcn_walls["on"]],
                "off_all": [round(w, 4) for w in bcn_walls["off"]],
            }
            log(f"fleet beacon A/B: {beacon_ab}")
            if not beacon_ab["within_budget"]:
                log(
                    "WARNING: fleet-beacon drain overhead "
                    f"{bcn_overhead * 100:.2f}% exceeds the 1% budget on "
                    "this host"
                )
        except Exception as e:  # fail-soft by design
            log(f"WARNING: beacon A/B leg failed ({e!r}); recorded as absent")

        # ---- elastic reshard matrix (benchmarks/reshard): N→M restores
        # across mesh shapes / axis orders / replication, bit-exact, with
        # origin bytes accounted against the theoretical overlap bytes
        # (target ≤ 1.1×) and origin/peer/cache attribution per cell.
        # Fail-soft: the drain trajectory must be written even if the
        # reshard harness can't run on this host.
        reshard_record = None
        try:
            renv = dict(os.environ)
            renv.setdefault("JAX_PLATFORMS", "cpu")
            renv.setdefault("RESHARD_BENCH_MB", "64")
            renv.setdefault("RESHARD_BENCH_FLEET_KS", "2")
            renv.setdefault("RESHARD_BENCH_FLEET_MB", "8")
            proc = subprocess.run(
                [sys.executable, "benchmarks/reshard/main.py"],
                env=renv,
                capture_output=True,
                text=True,
                timeout=1800,
            )
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr[-1500:])
            parsed = json.loads(proc.stdout.strip().splitlines()[-1])
            det = parsed["detail"]
            reshard_record = {
                "origin_ratio_worst": parsed["value"],
                "reshard_wall_s_max": det["reshard_wall_s_max"],
                "reshard_gbps_min": det["reshard_gbps_min"],
                "cells": det["cells"],
                "fleet": det["fleet"],
            }
            log(f"reshard matrix: {reshard_record}")
        except Exception as e:  # fail-soft by design
            log(f"WARNING: reshard bench failed ({e!r}); recorded as absent")

        # ---- fail-soft regression gate vs the best prior round on this
        # workload (same size_gb): drain wall, drain_vs_link, restore wall,
        # drain hash time, reshard wall, and the reshard origin-byte ratio
        # must not silently regress the way rounds 2→5 did. An empty
        # trajectory reports no_prior loudly; the round artifact is written
        # either way.
        gate = regression_gate(
            round(gb, 2),
            drain_s,
            drain_vs_link,
            restore_s,
            stage_hash_s=stage_breakdown.get("stage_hash_s", 0.0),
            link_probe=link_probe,
            reshard_wall_s=(
                reshard_record["reshard_wall_s_max"] if reshard_record else 0.0
            ),
            reshard_ratio=(
                reshard_record["origin_ratio_worst"] if reshard_record else 0.0
            ),
        )
        log(f"regression gate: {gate}")

        print(
            json.dumps(
                {
                    "metric": "train_step_stall_on_async_save",
                    "value": round(stall_s, 3),
                    "unit": "s",
                    "vs_baseline": round(ref_equiv_stall_s / stall_s, 1),
                    "detail": {
                        "size_gb": round(gb, 2),
                        "async_stall_s": round(stall_s, 3),
                        "async_stall_cold_s": round(cold_stall_s, 3),
                        "background_drain_s": round(drain_s, 2),
                        "drain_gbps": round(drain_gbps, 4),
                        "link_gbps_around_drain": round(link_gbps, 4),
                        "drain_vs_link": round(drain_vs_link, 2),
                        "link_probe": link_probe,
                        "stall_phases_s": stall_phases,
                        "drain_stats_s": drain_stats,
                        "stage_breakdown_s": stage_breakdown,
                        "regression_gate": gate,
                        "sync_drain_stats_s": sync_drains,
                        "target_stall_s": 5.0,
                        "steady_state": steady_record,
                        "stream_ab": stream_ab,
                        "sync_take_gbps": round(sync_gbps, 3),
                        "naive_save_gbps": round(naive_gbps, 3),
                        "speedup_vs_naive_sync": round(sync_gbps / naive_gbps, 2),
                        "ab_reps": ab_reps,
                        "sync_gbps_all": [round(r, 4) for r in sync_rates],
                        "naive_gbps_all": [round(r, 4) for r in naive_rates],
                        "ref_equiv_stall_s": round(ref_equiv_stall_s, 2),
                        "restore_bit_exact": ok,
                        "restore": restore_record,
                        "recorder_ab": recorder_ab,
                        "beacon_ab": beacon_ab,
                        "job_timeline": job_timeline,
                        "reshard": reshard_record,
                        "telemetry": telemetry_summary,
                        # Environment fingerprint: every TORCHSNAPSHOT_TPU_*
                        # knob in effect, plus an explicit record that fault
                        # injection was OFF — a benchmark run with the fault
                        # knob set would measure the injector, not the
                        # library, so its absence is part of the result's
                        # identity.
                        "env": {
                            "knobs": _knobs.env_fingerprint(),
                            "fault_injection": (
                                _knobs.get_faults_spec() or "disabled"
                            ),
                        },
                        "baseline": (
                            "reference-style async_take must capture to host RAM "
                            "before returning; its stall >= one full D2H transfer "
                            "at the rate measured on this same hardware"
                        ),
                    },
                }
            )
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
