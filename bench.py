"""Headline benchmark: checkpoint-save throughput from TPU HBM to local FS.

Mirrors the reference's flagship benchmark (``benchmarks/ddp/README.md``:
a 20 GB model saved with torch.save ~32 s vs torchsnapshot ~13.91 s on one
A100 + local FS => ~1.44 GB/s). Here: a transformer-shaped bf16 param pytree
living in TPU HBM is saved with ``Snapshot.take()`` to local FS; the metric
is end-to-end GB/s for the synchronous take (device->host transfer +
serialization + storage I/O, all overlapped by the scheduler).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N/1.438, ...}
Secondary numbers (async stall time, restore check) go to stderr.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

_BASELINE_GBPS = 20.0 / 13.91  # reference: 20 GB / 13.91 s, 1 GPU + local FS


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_params(total_gb: float):
    """Transformer-shaped bf16 params filling ~total_gb of HBM."""
    import jax
    import jax.numpy as jnp

    d_model, d_ff = 4096, 16384
    layer_bytes = (3 * d_model * d_model + 2 * d_model * d_ff) * 2  # bf16
    n_layers = max(1, int(total_gb * 1e9 / layer_bytes))

    @jax.jit
    def make_layer(key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "attn": jax.random.normal(k1, (d_model, 3 * d_model), jnp.bfloat16),
            "up": jax.random.normal(k2, (d_model, d_ff), jnp.bfloat16),
            "down": jax.random.normal(k3, (d_ff, d_model), jnp.bfloat16),
        }

    import jax.random as jrandom

    params = {}
    key = jrandom.PRNGKey(0)
    for i in range(n_layers):
        key, sub = jrandom.split(key)
        params[f"layer_{i}"] = make_layer(sub)
    import jax

    jax.block_until_ready(params)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(params))
    return params, nbytes


def main() -> None:
    from torchsnapshot_tpu import Snapshot, StateDict

    total_gb = float(os.environ.get("BENCH_TOTAL_GB", "8"))
    params, nbytes = build_params(total_gb)
    gb = nbytes / 1e9
    log(f"built {gb:.2f} GB of bf16 params on {_device_desc()}")

    root = tempfile.mkdtemp(prefix="tss_bench_")
    try:
        # Warmup on a small subset to exclude one-time costs (imports,
        # thread-pool spin-up, directory creation).
        warm = {"w": StateDict(p=next(iter(params.values()))["up"])}
        Snapshot.take(os.path.join(root, "warm"), warm)

        sd = StateDict(**params)
        t0 = time.perf_counter()
        Snapshot.take(os.path.join(root, "ckpt"), {"model": sd})
        take_s = time.perf_counter() - t0
        gbps = gb / take_s
        log(f"sync take: {take_s:.2f}s -> {gbps:.2f} GB/s")

        # Async stall: how long training is blocked.
        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(root, "ckpt_async"), {"model": sd})
        stall_s = time.perf_counter() - t0
        pending.wait()
        log(f"async take stall: {stall_s:.2f}s (train-step blocked time)")

        # Restore bit-exactness spot check on one layer via random access
        # (restore() would load the full snapshot; read_object fetches only
        # the probed leaves).
        snap = Snapshot(os.path.join(root, "ckpt"))
        first = next(iter(params))
        ok = all(
            np.array_equal(
                np.asarray(snap.read_object(f"0/model/{first}/{k}")).view(np.uint8),
                np.asarray(params[first][k]).view(np.uint8),
            )
            for k in params[first]
        )
        log(f"restore bit-exact: {ok}")
        if not ok:
            raise SystemExit("restore mismatch")

        print(
            json.dumps(
                {
                    "metric": "checkpoint_save_throughput",
                    "value": round(gbps, 3),
                    "unit": "GB/s",
                    "vs_baseline": round(gbps / _BASELINE_GBPS, 3),
                    "detail": {
                        "size_gb": round(gb, 2),
                        "sync_take_s": round(take_s, 2),
                        "async_stall_s": round(stall_s, 2),
                        "baseline": "torchsnapshot 20GB DDP save, 1 GPU + local FS, 1.438 GB/s",
                    },
                }
            )
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _device_desc() -> str:
    import jax

    d = jax.devices()[0]
    return f"{d.device_kind} ({d.platform})"


if __name__ == "__main__":
    main()
