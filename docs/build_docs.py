#!/usr/bin/env python
"""Buildable docs pipeline (analogue of the reference's sphinx build +
``build_docs.yaml`` publish, ``/root/reference/docs/source`` — the docs
here are markdown, so the build renders them to HTML and, more importantly,
**checks them**):

- every ```python fenced block must parse (``compile(..., "exec")``) —
  catches snippet typos/indentation the way sphinx doctest syntax does;
- every relative link/file reference of the form ``[..](path)`` must exist;
- renders ``docs/*.md`` + the READMEs into ``docs/build/html/`` with
  python-markdown when available (CI installs it; the checks above run
  with zero dependencies either way).

    python docs/build_docs.py            # check + render
    python docs/build_docs.py --check    # check only (no output tree)
"""

from __future__ import annotations

import argparse
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC_SOURCES = [
    "README.md",
    "benchmarks/README.md",
    "docs/getting_started.md",
    "docs/api_reference.md",
    "docs/utilities.md",
    "docs/observability.md",
    "docs/performance.md",
    "docs/robustness.md",
    "docs/lifecycle.md",
    "docs/static-analysis.md",
]

_FENCE_RE = re.compile(r"```(\w*)\n(.*?)```", re.DOTALL)
_LINK_RE = re.compile(r"\[[^\]]*\]\(([^)]+)\)")


def check_snippets(relpath: str, text: str) -> list[str]:
    problems = []
    for i, m in enumerate(_FENCE_RE.finditer(text)):
        lang, body = m.group(1), m.group(2)
        if lang != "python":
            continue
        lineno = text[: m.start()].count("\n") + 2
        try:
            compile(body, f"{relpath}:snippet{i}", "exec")
        except SyntaxError as e:
            problems.append(
                f"{relpath}:{lineno}: python snippet does not parse: {e.msg} "
                f"(snippet line {e.lineno})"
            )
    return problems


def check_links(relpath: str, text: str) -> list[str]:
    problems = []
    base = os.path.dirname(os.path.join(ROOT, relpath))
    for m in _LINK_RE.finditer(text):
        # Validate the file part of `path#anchor` links too.
        target = m.group(1).strip().partition("#")[0]
        if not target or re.match(r"^[a-z]+://", target) or target.startswith("mailto:"):
            continue
        if not os.path.exists(os.path.normpath(os.path.join(base, target))):
            lineno = text[: m.start()].count("\n") + 1
            problems.append(f"{relpath}:{lineno}: broken relative link: {target}")
    return problems


def render(relpath: str, text: str, out_dir: str) -> None:
    try:
        import markdown
    except ImportError:
        return  # checks already ran; rendering is CI's job
    html = markdown.markdown(text, extensions=["tables", "fenced_code"])
    name = relpath.replace("/", "_").replace(".md", ".html")
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, name), "w") as f:
        f.write(
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{relpath}</title></head><body>\n{html}\n</body></html>\n"
        )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--check", action="store_true", help="check only")
    args = parser.parse_args()

    out_dir = os.path.join(ROOT, "docs", "build", "html")
    problems: list[str] = []
    for relpath in DOC_SOURCES:
        with open(os.path.join(ROOT, relpath), encoding="utf-8") as f:
            text = f.read()
        problems += check_snippets(relpath, text)
        problems += check_links(relpath, text)
        if not args.check:
            render(relpath, text, out_dir)
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} docs problem(s)")
        sys.exit(1)
    print(f"docs OK ({len(DOC_SOURCES)} sources)", end="")
    print("" if args.check else f"; rendered to {os.path.relpath(out_dir, ROOT)}")


if __name__ == "__main__":
    main()
