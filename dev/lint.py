#!/usr/bin/env python
"""Self-contained lint gate (analogue of the reference's pre-commit hook,
``/root/reference/.github/workflows/pre_commit.yaml``) with zero
third-party dependencies, so the exact same check runs in CI and on any dev
box:

- every Python file must parse (syntax gate);
- unused imports (AST-walked; ``# noqa`` on the import line suppresses,
  ``__init__.py`` re-export lists are exempt);
- no tabs in indentation, no trailing whitespace, files end with a newline;
- generated benchmark tables in README.md / benchmarks/README.md match the
  newest ``BENCH_r*.json`` artifact (delegates to
  ``benchmarks/gen_tables.py --check``), so a driver-recorded regression can
  never stay invisible in the human-facing docs;
- the checkpoint-invariant static analyzer (``dev/analyze``: async-safety,
  task/future leaks, knob/telemetry drift, manifest schema, flow-sensitive
  resource balance, cross-thread mutation, fault-injection coverage,
  collective discipline — see ``docs/static-analysis.md``) over the
  library package.

    python dev/lint.py            # lint + analyze the repo
    python dev/lint.py FILES...   # lint specific files (analyzer runs too)
    python dev/lint.py --fix      # auto-fix trailing whitespace / missing
                                  # final newlines, then lint
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_DIRS = ("torchsnapshot_tpu", "tests", "benchmarks", "examples", "dev", "docs")
LINT_FILES = ("bench.py", "__graft_entry__.py")


def iter_targets(argv: list[str]) -> list[str]:
    if argv:
        return argv
    out = []
    for d in LINT_DIRS:
        for dirpath, _, filenames in os.walk(os.path.join(ROOT, d)):
            out.extend(
                os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
            )
    out.extend(os.path.join(ROOT, f) for f in LINT_FILES)
    return sorted(p for p in out if os.path.exists(p))


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # a.b.c -> record the root name
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    # Names referenced only in string annotations / docstring doctests are
    # not resolvable statically; __all__ strings count as uses.
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    return used


def unused_imports(tree: ast.AST, source_lines: list[str]) -> list:
    used = _used_names(tree)
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        line = source_lines[node.lineno - 1]
        if "noqa" in line:
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            if bound not in used:
                problems.append((node.lineno, f"unused import: {bound}"))
    return problems


def lint_file(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    problems = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = source.split("\n")
    if not os.path.basename(path) == "__init__.py":
        problems.extend(unused_imports(tree, lines))
    for i, line in enumerate(lines, 1):
        stripped = line.rstrip("\n")
        if stripped != stripped.rstrip():
            problems.append((i, "trailing whitespace"))
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            problems.append((i, "tab in indentation"))
    if source and not source.endswith("\n"):
        problems.append((len(lines), "no newline at end of file"))
    return problems


def fix_file(path: str) -> bool:
    """Auto-remediate the mechanical problems: trailing whitespace and a
    missing final newline. Returns True when the file changed. Tabs in
    indentation are NOT auto-fixed (the right width is a judgment call)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    if not source:
        return False
    fixed = "\n".join(line.rstrip() for line in source.split("\n"))
    if not fixed.endswith("\n"):
        fixed += "\n"
    if fixed == source:
        return False
    with open(path, "w", encoding="utf-8") as f:
        f.write(fixed)
    return True


def check_analyzer(paths: list) -> int:
    """The static-analysis gate (``python -m dev.analyze``): all ten
    passes (see dev/analyze/__init__.py). Subprocess so the analyzer's
    import path (repo root) never depends on how lint was invoked."""
    import subprocess

    cmd = [sys.executable, "-m", "dev.analyze", *paths]
    proc = subprocess.run(cmd, cwd=ROOT, capture_output=True, text=True)
    if proc.returncode != 0:
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        return 1
    return 0


def check_generated_tables() -> int:
    """Fail when the published tables drifted from the newest BENCH artifact."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "gen_tables.py"), "--check"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        return 1
    return 0


def main() -> None:
    argv = sys.argv[1:]
    fix = "--fix" in argv
    argv = [a for a in argv if a != "--fix"]
    failed = 0
    explicit_files = bool(argv)
    targets = iter_targets(argv)
    if fix:
        n_fixed = 0
        for path in targets:
            if fix_file(path):
                print(f"fixed: {os.path.relpath(path, ROOT)}")
                n_fixed += 1
        print(f"--fix: {n_fixed} file(s) rewritten")
    for path in targets:
        for lineno, msg in lint_file(path):
            print(f"{os.path.relpath(path, ROOT)}:{lineno}: {msg}")
            failed += 1
    if explicit_files:
        # Analyzer conventions apply to the library package; lint-on-save of
        # a test or tool file shouldn't trip library-only gates.
        lib_paths = [
            p
            for p in targets
            if os.path.relpath(p, ROOT).startswith("torchsnapshot_tpu" + os.sep)
        ]
        if lib_paths:
            failed += check_analyzer(lib_paths)
    else:
        failed += check_analyzer([])
        failed += check_generated_tables()
    if failed:
        print(f"\n{failed} lint problem(s)")
        sys.exit(1)
    print("lint clean")


if __name__ == "__main__":
    main()
