#!/usr/bin/env python
"""Self-contained lint gate (analogue of the reference's pre-commit hook,
``/root/reference/.github/workflows/pre_commit.yaml``) with zero
third-party dependencies, so the exact same check runs in CI and on any dev
box:

- every Python file must parse (syntax gate);
- unused imports (AST-walked; ``# noqa`` on the import line suppresses,
  ``__init__.py`` re-export lists are exempt);
- no tabs in indentation, no trailing whitespace, files end with a newline;
- generated benchmark tables in README.md / benchmarks/README.md match the
  newest ``BENCH_r*.json`` artifact (delegates to
  ``benchmarks/gen_tables.py --check``), so a driver-recorded regression can
  never stay invisible in the human-facing docs.

    python dev/lint.py            # lint the repo
    python dev/lint.py FILES...   # lint specific files
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_DIRS = ("torchsnapshot_tpu", "tests", "benchmarks", "examples", "dev", "docs")
LINT_FILES = ("bench.py", "__graft_entry__.py")


def iter_targets(argv: list[str]) -> list[str]:
    if argv:
        return argv
    out = []
    for d in LINT_DIRS:
        for dirpath, _, filenames in os.walk(os.path.join(ROOT, d)):
            out.extend(
                os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
            )
    out.extend(os.path.join(ROOT, f) for f in LINT_FILES)
    return sorted(p for p in out if os.path.exists(p))


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # a.b.c -> record the root name
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    # Names referenced only in string annotations / docstring doctests are
    # not resolvable statically; __all__ strings count as uses.
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    return used


def unused_imports(tree: ast.AST, source_lines: list[str]) -> list:
    used = _used_names(tree)
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        line = source_lines[node.lineno - 1]
        if "noqa" in line:
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            if bound not in used:
                problems.append((node.lineno, f"unused import: {bound}"))
    return problems


def lint_file(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    problems = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = source.split("\n")
    if not os.path.basename(path) == "__init__.py":
        problems.extend(unused_imports(tree, lines))
    for i, line in enumerate(lines, 1):
        stripped = line.rstrip("\n")
        if stripped != stripped.rstrip():
            problems.append((i, "trailing whitespace"))
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            problems.append((i, "tab in indentation"))
    if source and not source.endswith("\n"):
        problems.append((len(lines), "no newline at end of file"))
    return problems


def check_generated_tables() -> int:
    """Fail when the published tables drifted from the newest BENCH artifact."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "gen_tables.py"), "--check"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        return 1
    return 0


def main() -> None:
    failed = 0
    explicit_files = bool(sys.argv[1:])
    for path in iter_targets(sys.argv[1:]):
        for lineno, msg in lint_file(path):
            print(f"{os.path.relpath(path, ROOT)}:{lineno}: {msg}")
            failed += 1
    if not explicit_files:
        failed += check_generated_tables()
    if failed:
        print(f"\n{failed} lint problem(s)")
        sys.exit(1)
    print("lint clean")


if __name__ == "__main__":
    main()
