"""Framework for the checkpoint-invariant static analyzer.

The paper's performance story rests on conventions no interpreter enforces:
the asyncio pipelines must never block the event loop, every spawned task
must be reaped, every ``TORCHSNAPSHOT_TPU_*`` knob must route through
``utils/knobs.py`` and appear in the docs catalog, and every span/metric
must be in the observability catalog. This package makes each convention a
CI gate (run from ``dev/lint.py``), zero third-party dependencies.

Pass modules register in :data:`PASSES`; each exposes ``run(ctx)`` yielding
:class:`Finding`. Suppression:

- inline: ``# noqa: TSA101`` on the flagged line (bare ``# noqa`` works too);
- grandfathered: an entry in the checked-in baseline file
  (``dev/analyze/baseline.json``). Baseline entries are ``path:CODE:key``
  strings — no line numbers, so unrelated edits don't invalidate them.
  Stale entries (matching no current finding) are themselves errors, so the
  baseline can only shrink; ``--update-baseline`` rewrites it.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative
    line: int
    code: str  # TSA###
    message: str
    key: str  # line-independent id for baseline matching

    @property
    def baseline_id(self) -> str:
        return f"{self.path}:{self.code}:{self.key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9_,\s]+))?", re.IGNORECASE)


def is_suppressed(finding: Finding, lines: List[str]) -> bool:
    """Inline ``# noqa`` / ``# noqa: TSA101[,TSA102]`` on the flagged line."""
    if not 1 <= finding.line <= len(lines):
        return False
    m = _NOQA_RE.search(lines[finding.line - 1])
    if m is None:
        return False
    codes = m.group("codes")
    if codes is None:
        return True  # bare noqa suppresses everything
    return finding.code in {c.strip().upper() for c in codes.split(",")}


class AnalysisContext:
    """Parsed view of the files one analysis run covers.

    ``lib_files`` are the Python files the AST passes scan; ``knobs_path``
    is the knob registry module; ``catalog_path`` the markdown knob catalog;
    ``doc_files`` every doc scanned for dead knob mentions;
    ``telemetry_catalog_path`` the markdown holding the machine-readable
    span/metric catalog. All paths repo-relative; ``root`` is the repo root.
    Passes read files through :meth:`source`/:meth:`tree` (parsed once,
    cached); files that fail to parse produce one TSA000 finding and are
    skipped by every pass (``dev/lint.py``'s syntax gate reports details).
    """

    def __init__(
        self,
        root: str,
        lib_files: List[str],
        knobs_path: Optional[str] = None,
        catalog_path: Optional[str] = None,
        doc_files: Optional[List[str]] = None,
        telemetry_catalog_path: Optional[str] = None,
        telemetry_exempt_prefixes: Tuple[str, ...] = (),
        manifest_path: Optional[str] = None,
    ) -> None:
        self.root = root
        self.lib_files = lib_files
        self.knobs_path = knobs_path
        self.catalog_path = catalog_path
        self.doc_files = doc_files or []
        self.telemetry_catalog_path = telemetry_catalog_path
        self.telemetry_exempt_prefixes = telemetry_exempt_prefixes
        self.manifest_path = manifest_path
        self._sources: Dict[str, str] = {}
        self._trees: Dict[str, Optional[ast.AST]] = {}
        self.parse_failures: List[Finding] = []

    def source(self, relpath: str) -> str:
        if relpath not in self._sources:
            with open(os.path.join(self.root, relpath), encoding="utf-8") as f:
                self._sources[relpath] = f.read()
        return self._sources[relpath]

    def lines(self, relpath: str) -> List[str]:
        return self.source(relpath).split("\n")

    def tree(self, relpath: str) -> Optional[ast.AST]:
        if relpath not in self._trees:
            try:
                self._trees[relpath] = ast.parse(
                    self.source(relpath), filename=relpath
                )
            except SyntaxError as e:
                self._trees[relpath] = None
                self.parse_failures.append(
                    Finding(
                        path=relpath,
                        line=e.lineno or 0,
                        code="TSA000",
                        message=f"file does not parse: {e.msg}",
                        key="syntax",
                    )
                )
        return self._trees[relpath]


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child -> parent for every node; passes share this to find the
    statement context of an expression (retained vs discarded, with-item
    vs bare call)."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def dotted_name(func: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (calls on call
    results keep their trailing attribute path: ``().result`` -> None but
    ``x.submit().result`` -> None; only pure name chains resolve)."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_py_files(root: str, rel_dir: str) -> List[str]:
    out = []
    for dirpath, _, filenames in os.walk(os.path.join(root, rel_dir)):
        for f in filenames:
            if f.endswith(".py"):
                out.append(
                    os.path.relpath(os.path.join(dirpath, f), root)
                )
    return sorted(out)


def default_context(root: str) -> AnalysisContext:
    """The real repo's analysis scope: the library package, its knob
    registry, and the two markdown catalogs."""
    doc_files = sorted(
        os.path.relpath(os.path.join(root, "docs", f), root)
        for f in os.listdir(os.path.join(root, "docs"))
        if f.endswith(".md")
    )
    doc_files += [f for f in ("README.md",) if os.path.exists(os.path.join(root, f))]
    return AnalysisContext(
        root=root,
        lib_files=iter_py_files(root, "torchsnapshot_tpu"),
        knobs_path="torchsnapshot_tpu/utils/knobs.py",
        catalog_path="docs/utilities.md",
        doc_files=doc_files,
        telemetry_catalog_path="docs/observability.md",
        # The telemetry subsystem implements the machinery (generic
        # counter()/span() plumbing); the discipline passes gate its users.
        telemetry_exempt_prefixes=("torchsnapshot_tpu/telemetry/",),
        manifest_path="torchsnapshot_tpu/manifest.py",
    )


def get_passes():
    """(name, run) for every registered pass, import deferred so the CLI
    can list passes even if one module is mid-edit."""
    from . import (
        async_safety,
        knob_drift,
        manifest_schema,
        task_leak,
        telemetry_discipline,
    )

    return [
        ("async-safety", async_safety.run),
        ("task-leak", task_leak.run),
        ("knob-drift", knob_drift.run),
        ("telemetry-discipline", telemetry_discipline.run),
        ("manifest-schema", manifest_schema.run),
    ]


def run_passes(ctx: AnalysisContext) -> List[Finding]:
    """All passes over ``ctx``, inline-noqa already applied (markdown
    findings have no noqa mechanism — use the baseline)."""
    findings: List[Finding] = []
    for _, run in get_passes():
        findings.extend(run(ctx))
    findings.extend(ctx.parse_failures)
    out = []
    for f in findings:
        if f.path.endswith(".py") and is_suppressed(f, ctx.lines(f.path)):
            continue
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.code))


def load_baseline(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("findings", []))


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {
        "comment": (
            "Grandfathered dev/analyze findings. Entries are "
            "'path:CODE:key' (line-independent). Stale entries fail the "
            "gate; regenerate with: python -m dev.analyze --update-baseline"
        ),
        "findings": sorted(f.baseline_id for f in findings),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def apply_baseline(
    findings: List[Finding], baseline: List[str]
) -> Tuple[List[Finding], List[str]]:
    """(new findings, stale baseline entries). Multiset semantics: one
    baseline entry absorbs one finding, so a second identical violation in
    the same file still fails."""
    budget: Dict[str, int] = {}
    for entry in baseline:
        budget[entry] = budget.get(entry, 0) + 1
    fresh = []
    for f in findings:
        if budget.get(f.baseline_id, 0) > 0:
            budget[f.baseline_id] -= 1
        else:
            fresh.append(f)
    stale = sorted(
        entry for entry, remaining in budget.items() for _ in range(remaining)
    )
    return fresh, stale
