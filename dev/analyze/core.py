"""Framework for the checkpoint-invariant static analyzer.

The paper's performance story rests on conventions no interpreter enforces:
the asyncio pipelines must never block the event loop, every spawned task
must be reaped, every ``TORCHSNAPSHOT_TPU_*`` knob must route through
``utils/knobs.py`` and appear in the docs catalog, and every span/metric
must be in the observability catalog. This package makes each convention a
CI gate (run from ``dev/lint.py``), zero third-party dependencies.

Pass modules register in :data:`PASSES`; each exposes ``run(ctx)`` yielding
:class:`Finding`. Suppression:

- inline: ``# noqa: TSA101`` on the flagged line (bare ``# noqa`` works too);
- grandfathered: an entry in the checked-in baseline file
  (``dev/analyze/baseline.json``). Baseline entries are ``path:CODE:key``
  strings — no line numbers, so unrelated edits don't invalidate them.
  Stale entries (matching no current finding) are themselves errors, so the
  baseline can only shrink; ``--update-baseline`` rewrites it.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative
    line: int
    code: str  # TSA###
    message: str
    key: str  # line-independent id for baseline matching

    @property
    def baseline_id(self) -> str:
        return f"{self.path}:{self.code}:{self.key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9_,\s]+))?", re.IGNORECASE)


def is_suppressed(finding: Finding, lines: List[str]) -> bool:
    """Inline ``# noqa`` / ``# noqa: TSA101[,TSA102]`` on the flagged line."""
    if not 1 <= finding.line <= len(lines):
        return False
    m = _NOQA_RE.search(lines[finding.line - 1])
    if m is None:
        return False
    codes = m.group("codes")
    if codes is None:
        return True  # bare noqa suppresses everything
    return finding.code in {c.strip().upper() for c in codes.split(",")}


class AnalysisContext:
    """Parsed view of the files one analysis run covers.

    ``lib_files`` are the Python files the AST passes scan; ``knobs_path``
    is the knob registry module; ``catalog_path`` the markdown knob catalog;
    ``doc_files`` every doc scanned for dead knob mentions;
    ``telemetry_catalog_path`` the markdown holding the machine-readable
    span/metric catalog. All paths repo-relative; ``root`` is the repo root.
    Passes read files through :meth:`source`/:meth:`tree` (parsed once,
    cached); files that fail to parse produce one TSA000 finding and are
    skipped by every pass (``dev/lint.py``'s syntax gate reports details).
    """

    def __init__(
        self,
        root: str,
        lib_files: List[str],
        knobs_path: Optional[str] = None,
        catalog_path: Optional[str] = None,
        doc_files: Optional[List[str]] = None,
        telemetry_catalog_path: Optional[str] = None,
        telemetry_exempt_prefixes: Tuple[str, ...] = (),
        manifest_path: Optional[str] = None,
        io_types_path: Optional[str] = None,
        faults_path: Optional[str] = None,
    ) -> None:
        self.root = root
        self.lib_files = lib_files
        self.knobs_path = knobs_path
        self.catalog_path = catalog_path
        self.doc_files = doc_files or []
        self.telemetry_catalog_path = telemetry_catalog_path
        self.telemetry_exempt_prefixes = telemetry_exempt_prefixes
        self.manifest_path = manifest_path
        self.io_types_path = io_types_path
        self.faults_path = faults_path
        self._sources: Dict[str, str] = {}
        self._trees: Dict[str, Optional[ast.AST]] = {}
        self._parents: Dict[str, Dict[ast.AST, ast.AST]] = {}
        self.parse_failures: List[Finding] = []

    def source(self, relpath: str) -> str:
        if relpath not in self._sources:
            try:
                with open(
                    os.path.join(self.root, relpath), encoding="utf-8"
                ) as f:
                    self._sources[relpath] = f.read()
            except OSError as e:
                # Unreadable/missing file: ONE file:line finding (like a
                # syntax error) instead of a traceback out of every pass
                # that touches it.
                self._sources[relpath] = ""
                self._trees[relpath] = None
                self.parse_failures.append(
                    Finding(
                        path=relpath,
                        line=0,
                        code="TSA000",
                        message=f"file is not readable: {e.strerror or e}",
                        key="unreadable",
                    )
                )
        return self._sources[relpath]

    def lines(self, relpath: str) -> List[str]:
        return self.source(relpath).split("\n")

    def parents(self, relpath: str) -> Dict[ast.AST, ast.AST]:
        """The file's child->parent map, computed once and shared by every
        pass (task-leak, telemetry-discipline, thread-safety all need it)."""
        if relpath not in self._parents:
            tree = self.tree(relpath)
            self._parents[relpath] = {} if tree is None else parent_map(tree)
        return self._parents[relpath]

    def tree(self, relpath: str) -> Optional[ast.AST]:
        if relpath not in self._trees:
            source = self.source(relpath)  # may record an unreadable-file
            if relpath in self._trees:  # finding and pin the tree to None
                return self._trees[relpath]
            try:
                self._trees[relpath] = ast.parse(source, filename=relpath)
            except SyntaxError as e:
                self._trees[relpath] = None
                self.parse_failures.append(
                    Finding(
                        path=relpath,
                        line=e.lineno or 0,
                        code="TSA000",
                        message=f"file does not parse: {e.msg}",
                        key="syntax",
                    )
                )
        return self._trees[relpath]


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child -> parent for every node; passes share this to find the
    statement context of an expression (retained vs discarded, with-item
    vs bare call)."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def dotted_name(func: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (calls on call
    results keep their trailing attribute path: ``().result`` -> None but
    ``x.submit().result`` -> None; only pure name chains resolve)."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# Flow-sensitive statement walking (the TSA6xx resource-balance machinery).
#
# A full CFG for Python is overkill for the invariants this analyzer gates;
# what the balance pass needs is *path sensitivity over statements*: which
# abstract tokens (open budget debits) can be live when control reaches a
# statement, an `await`, an early return, or the function's end — including
# through if/else splits, loop back-edges, and try/except/finally. The
# engine below walks one function body with a set of abstract states (each
# state a frozenset of tokens), merging at joins and iterating loop bodies
# to a fixpoint. Exceptional control flow is approximated structurally: a
# statement that can raise either escapes the function (reported via
# ``on_unprotected_raise``) or is covered by an enclosing try whose
# handler/finally the subclass recognizes as *protecting* (releasing every
# token). Nested function definitions are opaque — each is walked as its
# own function by the pass driver.
# ---------------------------------------------------------------------------


class _LoopCtx:
    __slots__ = ("breaks", "continues")

    def __init__(self) -> None:
        self.breaks: set = set()
        self.continues: set = set()


class FlowWalker:
    """Abstract-state walker over ONE function body (see block comment).

    Subclass hooks — ``state`` is a frozenset of pass-defined tokens:

    - ``transfer(stmt, state) -> state``: effect of one simple statement;
    - ``branch(test, state) -> (true_states, false_states)``: effect of a
      branch condition (default: no effect on either side);
    - ``try_protects(trystmt) -> bool``: whether this try's handlers or
      finally release every live token on the exceptional path;
    - ``may_raise(stmt) -> bool``: whether the statement can raise;
    - ``on_await(stmt, state)``: a state observed at an ``await`` point
      with no protecting try enclosing it;
    - ``on_unprotected_raise(stmt, state)``: a state at a may-raise
      statement with no protecting try enclosing it;
    - ``on_exit(node, state, how)``: a state reaching function exit
      (``how`` is "return" or "end").
    """

    _MAX_LOOP_PASSES = 8

    def walk(self, fn: ast.AST) -> None:
        out = self._body(list(fn.body), {frozenset()}, 0, None)
        for state in out:
            self.on_exit(fn, state, "end")

    # -- hooks (defaults are no-ops) ----------------------------------------
    def transfer(self, stmt: ast.stmt, state: frozenset) -> frozenset:
        return state

    def branch(self, test: ast.expr, state: frozenset):
        return {state}, {state}

    def try_protects(self, trystmt: ast.Try) -> bool:
        return False

    def may_raise(self, stmt: ast.stmt) -> bool:
        return False

    def on_await(self, stmt: ast.stmt, state: frozenset) -> None:
        pass

    def on_unprotected_raise(self, stmt: ast.stmt, state: frozenset) -> None:
        pass

    def on_exit(self, node: ast.AST, state: frozenset, how: str) -> None:
        pass

    # -- engine -------------------------------------------------------------
    def _body(self, stmts, states: set, protected: int, loop: Optional[_LoopCtx]) -> set:
        for stmt in stmts:
            if not states:
                return states
            states = self._stmt(stmt, states, protected, loop)
        return states

    def _stmt(self, stmt, states: set, protected: int, loop) -> set:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return states  # nested scopes are walked separately
        if isinstance(stmt, ast.If):
            out: set = set()
            for state in states:
                true_states, false_states = self.branch(stmt.test, state)
                out |= self._body(list(stmt.body), set(true_states), protected, loop)
                if stmt.orelse:
                    out |= self._body(
                        list(stmt.orelse), set(false_states), protected, loop
                    )
                else:
                    out |= set(false_states)
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, states, protected, loop)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, states, protected, loop)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # Item expressions behave like one simple statement (a synthetic
            # Expr, so the body isn't double-walked), then the body runs in
            # the same protection context.
            items = ast.Expr(
                value=ast.Tuple(
                    elts=[item.context_expr for item in stmt.items],
                    ctx=ast.Load(),
                ),
                lineno=stmt.lineno,
                col_offset=stmt.col_offset,
            )
            states = self._simple(items, states, protected)
            return self._body(list(stmt.body), states, protected, loop)
        if isinstance(stmt, ast.Return):
            states = self._simple(stmt, states, protected)
            for state in states:
                self.on_exit(stmt, state, "return")
            return set()
        if isinstance(stmt, ast.Break):
            if loop is not None:
                loop.breaks |= states
            return set()
        if isinstance(stmt, ast.Continue):
            if loop is not None:
                loop.continues |= states
            return set()
        if isinstance(stmt, ast.Raise):
            if protected == 0:
                for state in states:
                    self.on_unprotected_raise(stmt, state)
            return set()
        return self._simple(stmt, states, protected)

    def _simple(self, stmt, states: set, protected: int) -> set:
        out = set()
        has_await = any(isinstance(n, ast.Await) for n in ast.walk(stmt))
        for state in states:
            # The statement is treated atomically, and the raise/await check
            # sees only tokens live BOTH before and after it: releases and
            # handoffs inside the statement already closed theirs, and an
            # acquisition inside a raising statement never happened.
            new = self.transfer(stmt, state)
            live = frozenset(set(state) & set(new))
            if protected == 0:
                if has_await:
                    self.on_await(stmt, live)
                elif self.may_raise(stmt):
                    self.on_unprotected_raise(stmt, live)
            out.add(new)
        return out

    def _loop(self, stmt, states: set, protected: int, outer) -> set:
        lc = _LoopCtx()
        if isinstance(stmt, ast.While):
            entry = set()
            for state in states:
                true_states, false_states = self.branch(stmt.test, state)
                entry |= set(true_states)
                lc.breaks |= set(false_states)  # loop may run zero times
        else:
            entry = self._simple(
                ast.Expr(value=stmt.iter, lineno=stmt.lineno, col_offset=0),
                states,
                protected,
            )
            lc.breaks |= entry  # zero iterations
        seen = set(entry)
        frontier = set(entry)
        for _ in range(self._MAX_LOOP_PASSES):
            if not frontier:
                break
            out = self._body(list(stmt.body), frontier, protected, lc)
            out |= lc.continues
            lc.continues = set()
            if isinstance(stmt, ast.While):
                nxt = set()
                for state in out:
                    true_states, false_states = self.branch(stmt.test, state)
                    nxt |= set(true_states)
                    lc.breaks |= set(false_states)
            else:
                nxt = out
                lc.breaks |= out  # iterator exhausted
            frontier = nxt - seen
            seen |= nxt
        after = set(lc.breaks)
        if stmt.orelse:
            after = self._body(list(stmt.orelse), after, protected, outer)
        return after

    def _try(self, stmt: ast.Try, states: set, protected: int, loop) -> set:
        protecting = self.try_protects(stmt)
        body_out = self._body(
            list(stmt.body), set(states), protected + (1 if protecting else 0), loop
        )
        # Handler entry is approximated as "anywhere in the body": the union
        # of the entry states and the body's exit states.
        handler_entry = set(states) | body_out
        after = set(body_out)
        for handler in stmt.handlers:
            after |= self._body(list(handler.body), set(handler_entry), protected, loop)
        if stmt.orelse:
            after = self._body(list(stmt.orelse), after, protected, loop)
        if stmt.finalbody:
            after = self._body(list(stmt.finalbody), after, protected, loop)
        return after


def iter_functions(tree: ast.AST):
    """Every function definition in the file (module-level, methods, and
    nested defs alike) — each is flow-walked independently."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def iter_py_files(root: str, rel_dir: str) -> List[str]:
    out = []
    for dirpath, _, filenames in os.walk(os.path.join(root, rel_dir)):
        for f in filenames:
            if f.endswith(".py"):
                out.append(
                    os.path.relpath(os.path.join(dirpath, f), root)
                )
    return sorted(out)


def default_context(root: str) -> AnalysisContext:
    """The real repo's analysis scope: the library package, its knob
    registry, and the two markdown catalogs."""
    doc_files = sorted(
        os.path.relpath(os.path.join(root, "docs", f), root)
        for f in os.listdir(os.path.join(root, "docs"))
        if f.endswith(".md")
    )
    doc_files += [f for f in ("README.md",) if os.path.exists(os.path.join(root, f))]
    return AnalysisContext(
        root=root,
        lib_files=iter_py_files(root, "torchsnapshot_tpu"),
        knobs_path="torchsnapshot_tpu/utils/knobs.py",
        catalog_path="docs/utilities.md",
        doc_files=doc_files,
        telemetry_catalog_path="docs/observability.md",
        # The telemetry subsystem implements the machinery (generic
        # counter()/span() plumbing); the discipline passes gate its users.
        telemetry_exempt_prefixes=("torchsnapshot_tpu/telemetry/",),
        manifest_path="torchsnapshot_tpu/manifest.py",
        io_types_path="torchsnapshot_tpu/io_types.py",
        faults_path="torchsnapshot_tpu/faults.py",
    )


def get_passes():
    """(name, run) for every registered pass, import deferred so the CLI
    can list passes even if one module is mid-edit."""
    from . import (
        async_safety,
        collective_discipline,
        durability_discipline,
        fault_coverage,
        knob_drift,
        manifest_schema,
        resource_balance,
        task_leak,
        telemetry_discipline,
        thread_safety,
    )

    return [
        ("async-safety", async_safety.run),
        ("task-leak", task_leak.run),
        ("knob-drift", knob_drift.run),
        ("telemetry-discipline", telemetry_discipline.run),
        ("manifest-schema", manifest_schema.run),
        ("resource-balance", resource_balance.run),
        ("thread-safety", thread_safety.run),
        ("fault-coverage", fault_coverage.run),
        ("collective-discipline", collective_discipline.run),
        ("durability-discipline", durability_discipline.run),
    ]


# Sharding scope for ``--jobs``: a "file" pass derives each finding from one
# lib file in isolation (the catalogs it consults are read-only inputs), so
# it is safe to fan out over disjoint file shards. A "repo" pass does
# cross-file or registry/contract analysis (knob drift emits once per
# registry entry, fault coverage and TSA1004 walk the whole commit-point
# inventory) and must run exactly once, on the full context, in the parent.
PASS_SCOPES: Dict[str, str] = {
    "async-safety": "file",
    "task-leak": "file",
    "knob-drift": "repo",
    "telemetry-discipline": "file",
    "manifest-schema": "repo",
    "resource-balance": "file",
    "thread-safety": "file",
    "fault-coverage": "repo",
    "collective-discipline": "file",
    "durability-discipline": "repo",
}


def _context_spec(ctx: AnalysisContext) -> Dict:
    """Picklable constructor kwargs (minus ``lib_files``) for rebuilding an
    equivalent context inside a ``--jobs`` worker process."""
    return {
        "root": ctx.root,
        "knobs_path": ctx.knobs_path,
        "catalog_path": ctx.catalog_path,
        "doc_files": ctx.doc_files,
        "telemetry_catalog_path": ctx.telemetry_catalog_path,
        "telemetry_exempt_prefixes": ctx.telemetry_exempt_prefixes,
        "manifest_path": ctx.manifest_path,
        "io_types_path": ctx.io_types_path,
        "faults_path": ctx.faults_path,
    }


def _run_file_shard(spec: Dict, shard: List[str]):
    """Worker entry point: every file-scoped pass over one shard of lib
    files. Returns (findings, per-pass wall seconds); parse failures ride
    along as findings so the parent needn't re-parse broken files."""
    ctx = AnalysisContext(lib_files=shard, **spec)
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    for name, run in get_passes():
        if PASS_SCOPES.get(name, "repo") != "file":
            continue
        t0 = time.perf_counter()
        findings.extend(run(ctx))
        timings[name] = time.perf_counter() - t0
    findings.extend(ctx.parse_failures)
    return findings, timings


def _run_parallel(
    ctx: AnalysisContext, jobs: int, timings: Optional[Dict[str, float]]
) -> List[Finding]:
    import concurrent.futures

    spec = _context_spec(ctx)
    # Round-robin over the sorted file list spreads the handful of large
    # modules (snapshot.py, scheduler.py) across shards.
    shards = [ctx.lib_files[i::jobs] for i in range(jobs)]
    shards = [s for s in shards if s]
    findings: List[Finding] = []
    with concurrent.futures.ProcessPoolExecutor(max_workers=len(shards)) as pool:
        futures = [pool.submit(_run_file_shard, spec, s) for s in shards]
        for fut in futures:
            shard_findings, shard_timings = fut.result()
            findings.extend(shard_findings)
            if timings is not None:
                for name, dt in shard_timings.items():
                    timings[name] = timings.get(name, 0.0) + dt
    for name, run in get_passes():
        if PASS_SCOPES.get(name, "repo") != "repo":
            continue
        t0 = time.perf_counter()
        findings.extend(run(ctx))
        if timings is not None:
            timings[name] = timings.get(name, 0.0) + time.perf_counter() - t0
    findings.extend(ctx.parse_failures)
    # Workers and the parent's repo passes may both parse a broken file and
    # record its TSA000; identical findings collapse (order-preserving).
    return list(dict.fromkeys(findings))


def run_passes(
    ctx: AnalysisContext,
    jobs: int = 1,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """All passes over ``ctx``, inline-noqa already applied (markdown
    findings have no noqa mechanism — use the baseline).

    ``jobs > 1`` fans the file-scoped passes out over worker processes
    (repo-scoped passes still run here); ``timings``, when a dict, is
    filled with per-pass wall seconds (summed across workers, so parallel
    numbers read as CPU cost, not latency)."""
    if jobs > 1 and len(ctx.lib_files) > 1:
        findings = _run_parallel(ctx, jobs, timings)
    else:
        findings = []
        for name, run in get_passes():
            t0 = time.perf_counter()
            findings.extend(run(ctx))
            if timings is not None:
                timings[name] = timings.get(name, 0.0) + time.perf_counter() - t0
        findings.extend(ctx.parse_failures)
    out = []
    for f in findings:
        if f.path.endswith(".py") and is_suppressed(f, ctx.lines(f.path)):
            continue
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.code))


def load_baseline(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("findings", []))


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {
        "comment": (
            "Grandfathered dev/analyze findings. Entries are "
            "'path:CODE:key' (line-independent). Stale entries fail the "
            "gate; regenerate with: python -m dev.analyze --update-baseline"
        ),
        "findings": sorted(f.baseline_id for f in findings),
    }
    with open(path, "w", encoding="utf-8") as f:
        # Sorted entries (above) + sorted keys: --update-baseline output is
        # byte-deterministic, so baseline diffs review as pure adds/removes.
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(
    findings: List[Finding], baseline: List[str]
) -> Tuple[List[Finding], List[str]]:
    """(new findings, stale baseline entries). Multiset semantics: one
    baseline entry absorbs one finding, so a second identical violation in
    the same file still fails."""
    budget: Dict[str, int] = {}
    for entry in baseline:
        budget[entry] = budget.get(entry, 0) + 1
    fresh = []
    for f in findings:
        if budget.get(f.baseline_id, 0) > 0:
            budget[f.baseline_id] -= 1
        else:
            fresh.append(f)
    stale = sorted(
        entry for entry, remaining in budget.items() for _ in range(remaining)
    )
    return fresh, stale
