"""Checkpoint-invariant static analyzer (the ``dev/lint.py`` analysis gate).

Ten AST passes over the library, zero third-party dependencies:

1. async-safety (TSA1xx) — no blocking calls on the event loop;
2. task-leak (TSA2xx) — every spawned task AND executor future retained
   and reaped;
3. knob-drift (TSA3xx) — env knobs live in ``utils/knobs.py`` and the docs
   catalog, bidirectionally;
4. telemetry-discipline (TSA4xx) — spans context-managed, names cataloged;
5. manifest-schema (TSA5xx) — Entry fields stay JSON-serializable;
6. resource-balance (TSA6xx) — flow-sensitive: every budget debit / lane
   admission credited, handed off, or try/finally-protected on every path;
7. thread-safety (TSA7xx) — no unguarded attribute mutation shared between
   executor threads and the event loop;
8. fault-coverage (TSA8xx) — every StoragePlugin/StorageWriteStream op
   wrapped by FaultyStoragePlugin's injection map;
9. collective-discipline (TSA9xx) — collective call sequences stay
   SPMD-pure: no collective behind rank/time/filesystem/exception-derived
   branches, none in except/finally handlers, none per-iteration of
   divergent loops, and plan-affecting functions read only
   manifest/knob/entry state;
10. durability-discipline (TSA10xx) — flow-sensitive crash consistency:
    durable writes go through an atomic-commit idiom, catalog publishes
    are dominated by the data commit, GC deletes are keep-set gated, and
    every commit-point function stays pinned to a ``faults.py``
    kill-point op class.

Run: ``python -m dev.analyze`` (``--jobs N`` fans per-file passes out to
worker processes; ``--timings`` prints a per-pass wall-time report), or
via ``python dev/lint.py``.
See ``docs/static-analysis.md`` for codes, suppression, and the baseline
workflow.
"""

from .core import (
    AnalysisContext,
    Finding,
    apply_baseline,
    default_context,
    get_passes,
    load_baseline,
    run_passes,
    write_baseline,
)

__all__ = [
    "AnalysisContext",
    "Finding",
    "apply_baseline",
    "default_context",
    "get_passes",
    "load_baseline",
    "run_passes",
    "write_baseline",
]
