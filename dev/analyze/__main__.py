"""CLI for the checkpoint-invariant static analyzer.

    python -m dev.analyze                    # analyze the repo, apply baseline
    python -m dev.analyze --update-baseline  # grandfather current findings
    python -m dev.analyze FILES...           # AST passes on specific files
                                             # (doc-drift passes still run
                                             # against the repo catalogs)

Exit 0 when nothing new is found AND no baseline entry is stale; 1 otherwise.
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import (
    apply_baseline,
    default_context,
    get_passes,
    load_baseline,
    run_passes,
    write_baseline,
)

DEFAULT_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dev.analyze", description=__doc__.split("\n")[0]
    )
    parser.add_argument("files", nargs="*", help="restrict AST passes to these files")
    parser.add_argument("--root", default=DEFAULT_ROOT)
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    parser.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=1,
        metavar="N",
        help=(
            "fan file-scoped passes out over N worker processes "
            "(0 = cpu count; repo-scoped passes always run in the parent)"
        ),
    )
    parser.add_argument(
        "--timings",
        action="store_true",
        help="print a per-pass wall-time report after the run",
    )
    args = parser.parse_args(argv)

    if args.list_passes:
        for name, _ in get_passes():
            print(name)
        return 0

    ctx = default_context(args.root)
    if args.files:
        # Missing/unreadable files surface as one-line TSA000 findings from
        # the context (never a traceback) — same contract as syntax errors.
        ctx.lib_files = sorted(
            os.path.relpath(os.path.abspath(f), args.root) for f in args.files
        )
    jobs = args.jobs if args.jobs > 0 else (os.cpu_count() or 1)
    timings = {} if args.timings else None
    findings = run_passes(ctx, jobs=jobs, timings=timings)
    if timings is not None:
        print("per-pass wall time (summed across workers):")
        for name, dt in sorted(timings.items(), key=lambda kv: -kv[1]):
            print(f"  {name:<24} {dt * 1000:8.1f} ms")
        print(f"  {'total':<24} {sum(timings.values()) * 1000:8.1f} ms")

    if args.update_baseline:
        write_baseline(args.baseline, findings)
        print(
            f"baseline updated: {len(findings)} grandfathered finding(s) -> "
            f"{os.path.relpath(args.baseline, args.root)}"
        )
        return 0

    fresh, stale = apply_baseline(findings, load_baseline(args.baseline))
    for f in fresh:
        print(f.render())
    for entry in stale:
        print(f"stale baseline entry (fixed? remove it): {entry}")
    if fresh or stale:
        print(
            f"\n{len(fresh)} analyzer finding(s), {len(stale)} stale "
            "baseline entr(ies) — see docs/static-analysis.md"
        )
        return 1
    n_base = len(load_baseline(args.baseline))
    suffix = f" ({n_base} grandfathered)" if n_base else ""
    print(
        f"analyzer clean: {len(ctx.lib_files)} files, "
        f"{len(get_passes())} passes{suffix}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
