"""CLI for the checkpoint-invariant static analyzer.

    python -m dev.analyze                    # analyze the repo, apply baseline
    python -m dev.analyze --update-baseline  # grandfather current findings
    python -m dev.analyze FILES...           # AST passes on specific files
                                             # (doc-drift passes still run
                                             # against the repo catalogs)

Exit 0 when nothing new is found AND no baseline entry is stale; 1 otherwise.
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import (
    apply_baseline,
    default_context,
    get_passes,
    load_baseline,
    run_passes,
    write_baseline,
)

DEFAULT_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dev.analyze", description=__doc__.split("\n")[0]
    )
    parser.add_argument("files", nargs="*", help="restrict AST passes to these files")
    parser.add_argument("--root", default=DEFAULT_ROOT)
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    parser.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    args = parser.parse_args(argv)

    if args.list_passes:
        for name, _ in get_passes():
            print(name)
        return 0

    ctx = default_context(args.root)
    if args.files:
        # Missing/unreadable files surface as one-line TSA000 findings from
        # the context (never a traceback) — same contract as syntax errors.
        ctx.lib_files = sorted(
            os.path.relpath(os.path.abspath(f), args.root) for f in args.files
        )
    findings = run_passes(ctx)

    if args.update_baseline:
        write_baseline(args.baseline, findings)
        print(
            f"baseline updated: {len(findings)} grandfathered finding(s) -> "
            f"{os.path.relpath(args.baseline, args.root)}"
        )
        return 0

    fresh, stale = apply_baseline(findings, load_baseline(args.baseline))
    for f in fresh:
        print(f.render())
    for entry in stale:
        print(f"stale baseline entry (fixed? remove it): {entry}")
    if fresh or stale:
        print(
            f"\n{len(fresh)} analyzer finding(s), {len(stale)} stale "
            "baseline entr(ies) — see docs/static-analysis.md"
        )
        return 1
    n_base = len(load_baseline(args.baseline))
    suffix = f" ({n_base} grandfathered)" if n_base else ""
    print(
        f"analyzer clean: {len(ctx.lib_files)} files, "
        f"{len(get_passes())} passes{suffix}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
