"""Pass 8 — fault-injection coverage drift (TSA801-TSA803).

Every crash-consistency guarantee is only as strong as the chaos harness's
coverage, and the harness reaches storage exclusively through
``FaultyStoragePlugin`` (``faults.py``). Plugin surface added after the
wrapper was written — the way ``list_prefix`` (gc) and the telemetry
artifact path were bolted on post-hoc — silently bypasses fault injection:
the op works in every chaos schedule because no schedule can touch it.
This pass pins the wrapper to the contract:

- **TSA801** — a public ``async`` method on the wrapped contract class
  (``StoragePlugin`` / ``StorageWriteStream`` in ``io_types.py``) with no
  override on its wrapper (``FaultyStoragePlugin`` / ``_FaultyWriteStream``)
  — calls fall through to the inner plugin uninjected.
- **TSA802** — a wrapper override that never routes through ``_guard`` and
  is not declared in ``faults.py``'s ``_PASSTHROUGH_OPS`` tuple (the
  reviewable allowlist for genuinely non-data-plane ops like ``close``).
- **TSA803** — a ``_guard("<op>", ...)`` literal not present in the
  ``_OPS`` tuple: a typo'd op class matches no rule, so that injection
  point silently never fires.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding

# (contract class in io_types, wrapper class in faults)
_WRAP_PAIRS: Tuple[Tuple[str, str], ...] = (
    ("StoragePlugin", "FaultyStoragePlugin"),
    ("StorageWriteStream", "_FaultyWriteStream"),
)


def _class(tree: ast.AST, name: str) -> Optional[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _async_methods(cls: ast.ClassDef) -> Dict[str, int]:
    """{public async method name: line}."""
    return {
        node.name: node.lineno
        for node in cls.body
        if isinstance(node, ast.AsyncFunctionDef)
        and not node.name.startswith("_")
    }


def _methods(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {
        node.name: node
        for node in cls.body
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _string_tuple(tree: ast.AST, var: str) -> Optional[Set[str]]:
    """The string elements of a module-level ``var = ("a", "b", ...)``."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == var for t in node.targets
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            out = set()
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.add(elt.value)
            return out
    return None


def _guard_calls(fn: ast.AST) -> List[ast.Call]:
    out = []
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "_guard"
        ):
            out.append(node)
    return out


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    if ctx.io_types_path is None or ctx.faults_path is None:
        return findings
    contract_tree = ctx.tree(ctx.io_types_path)
    faults_tree = ctx.tree(ctx.faults_path)
    if contract_tree is None or faults_tree is None:
        return findings

    passthrough = _string_tuple(faults_tree, "_PASSTHROUGH_OPS") or set()
    ops = _string_tuple(faults_tree, "_OPS") or set()

    for contract_name, wrapper_name in _WRAP_PAIRS:
        contract = _class(contract_tree, contract_name)
        wrapper = _class(faults_tree, wrapper_name)
        if contract is None or wrapper is None:
            continue
        surface = _async_methods(contract)
        wrapped = _methods(wrapper)
        for method, line in sorted(surface.items()):
            if method not in wrapped:
                findings.append(
                    Finding(
                        path=ctx.io_types_path,
                        line=line,
                        code="TSA801",
                        message=(
                            f"`{contract_name}.{method}` has no override on "
                            f"`{wrapper_name}` ({ctx.faults_path}): calls "
                            "bypass fault injection — wrap it (route "
                            "through _guard) or declare it in "
                            "_PASSTHROUGH_OPS"
                        ),
                        key=f"unwrapped:{contract_name}.{method}",
                    )
                )
                continue
            if not _guard_calls(wrapped[method]) and method not in passthrough:
                findings.append(
                    Finding(
                        path=ctx.faults_path,
                        line=wrapped[method].lineno,
                        code="TSA802",
                        message=(
                            f"`{wrapper_name}.{method}` proxies without a "
                            "_guard injection point and is not declared in "
                            "_PASSTHROUGH_OPS — chaos schedules can never "
                            "fault this op"
                        ),
                        key=f"unguarded:{wrapper_name}.{method}",
                    )
                )

    # TSA803: every _guard op literal must be a declared op class.
    if ops:
        for node in ast.walk(faults_tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_guard"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value not in ops
            ):
                findings.append(
                    Finding(
                        path=ctx.faults_path,
                        line=node.lineno,
                        code="TSA803",
                        message=(
                            f"_guard op `{node.args[0].value}` is not in "
                            "_OPS: no fault rule can ever match it, so the "
                            "injection point silently never fires"
                        ),
                        key=f"badop:{node.args[0].value}",
                    )
                )
    return findings
