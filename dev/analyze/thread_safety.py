"""Pass 7 — cross-thread mutation discipline (TSA701/TSA702).

The pipeline core deliberately mixes the asyncio event loop with worker
threads: staging/serialize thunks, hash folds, and D2H lane resolves all run
on executors while the loop mutates the same pipeline objects. State shared
across that boundary must be either lock-guarded (``StageTimes``,
``TransferLanes`` hold a ``threading.Lock``) or of a thread-safe type
(``ProgressTracker``, queues). A plain attribute assigned from both sides is
a data race the event-loop design otherwise makes easy to miss — the thread
*looks* sequential from the coroutine that awaits it.

Detection, per file:

- **executor callables** are function defs (or lambdas) passed to
  ``*.submit(...)``, ``loop.run_in_executor(...)``, ``asyncio.to_thread(...)``
  or ``threading.Thread(target=...)`` — by name or inline;
- an **attribute write** is an ``Assign``/``AugAssign`` whose target is an
  attribute (``self.x = ...``, ``obj.x += ...``);
- a write is **guarded** when an enclosing ``with`` item's context
  expression mentions a lock (dotted name whose last segment contains
  ``lock``, e.g. ``with self._lock:``).

Codes:

- **TSA701** — an attribute assigned both inside an executor callable and
  in loop-side code (outside ``__init__``), with at least one side
  unguarded. Attributes initialized from an allowlisted thread-safe
  constructor (``ProgressTracker``, ``StageTimes``, ``Queue``, ``deque``,
  ``Lock``/``RLock``/``Condition``/``Semaphore``/``Event``,
  ``ThreadPoolExecutor``, ``Counter``) are exempt — mutating *through* such
  objects is method calls, which this pass never flags.
- **TSA702** — a ``nonlocal`` name rebound inside an executor callable that
  is also bound in the enclosing loop-side scope, unguarded (the closure
  analogue of TSA701).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import AnalysisContext, Finding, dotted_name

_SUBMIT_SUFFIXES = ("submit", "to_thread")
_RUN_IN_EXECUTOR = "run_in_executor"

_THREAD_SAFE_CTORS = {
    "ProgressTracker",
    "StageTimes",
    "Queue",
    "SimpleQueue",
    "LifoQueue",
    "PriorityQueue",
    "deque",
    "Counter",
    "Event",
    "Lock",
    "RLock",
    "Condition",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "ThreadPoolExecutor",
    "TransferLanes",
}


def _callable_args(call: ast.Call) -> List[ast.expr]:
    """The argument positions that name the submitted callable."""
    name = dotted_name(call.func)
    last = None
    if name is not None:
        last = name.rsplit(".", 1)[-1]
    elif isinstance(call.func, ast.Attribute):
        last = call.func.attr
    if last is None:
        return []
    if last == _RUN_IN_EXECUTOR:
        # loop.run_in_executor(executor, fn, *args)
        return call.args[1:2]
    if last in _SUBMIT_SUFFIXES:
        return call.args[:1]
    if last == "Thread":
        return [kw.value for kw in call.keywords if kw.arg == "target"]
    return []


class _Write:
    __slots__ = ("attr", "line", "in_executor", "guarded", "fn_name")

    def __init__(self, attr, line, in_executor, guarded, fn_name) -> None:
        self.attr = attr
        self.line = line
        self.in_executor = in_executor
        self.guarded = guarded
        self.fn_name = fn_name


def _is_lock_item(expr: ast.expr) -> bool:
    name = dotted_name(expr)
    if name is None:
        if isinstance(expr, ast.Call):
            return _is_lock_item(expr.func)
        return False
    return "lock" in name.rsplit(".", 1)[-1].lower()


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.lib_files:
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        parents = ctx.parents(relpath)

        # 1. Names (and inline defs) submitted to executors/threads.
        submitted_names: Set[str] = set()
        inline_defs: Set[ast.AST] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            for arg in _callable_args(node):
                if isinstance(arg, ast.Name):
                    submitted_names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    inline_defs.add(arg)

        executor_fns: Set[ast.AST] = set(inline_defs)
        for node in ast.walk(tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in submitted_names
            ):
                executor_fns.add(node)
        if not executor_fns:
            continue

        def enclosing_info(node) -> Dict[str, object]:
            """(is the node inside an executor callable?, is it guarded by a
            lock `with`?, the name of its directly-enclosing function)"""
            in_executor = False
            guarded = False
            fn_name: Optional[str] = None
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.With, ast.AsyncWith)) and any(
                    _is_lock_item(item.context_expr) for item in cur.items
                ):
                    guarded = True
                if isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    if fn_name is None and not isinstance(cur, ast.Lambda):
                        fn_name = cur.name
                    if cur in executor_fns:
                        in_executor = True
                cur = parents.get(cur)
            return {
                "in_executor": in_executor,
                "guarded": guarded,
                "fn_name": fn_name or "<module>",
            }

        # 2. Attribute writes + thread-safe-typed attributes.
        writes: List[_Write] = []
        safe_attrs: Set[str] = set()
        for node in ast.walk(tree):
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                # `self.x = Queue()` marks x as an allowlisted type.
                if isinstance(node.value, ast.Call):
                    ctor = dotted_name(node.value.func)
                    if (
                        ctor is not None
                        and ctor.rsplit(".", 1)[-1] in _THREAD_SAFE_CTORS
                    ):
                        for t in targets:
                            if isinstance(t, ast.Attribute):
                                safe_attrs.add(t.attr)
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            else:
                continue
            for t in targets:
                if not isinstance(t, ast.Attribute):
                    continue
                info = enclosing_info(node)
                writes.append(
                    _Write(
                        t.attr,
                        node.lineno,
                        info["in_executor"],
                        info["guarded"],
                        info["fn_name"],
                    )
                )

        by_attr: Dict[str, List[_Write]] = {}
        for w in writes:
            by_attr.setdefault(w.attr, []).append(w)
        for attr, ws in sorted(by_attr.items()):
            if attr in safe_attrs:
                continue
            executor_ws = [w for w in ws if w.in_executor]
            loop_ws = [
                w for w in ws if not w.in_executor and w.fn_name != "__init__"
            ]
            if not executor_ws or not loop_ws:
                continue
            unguarded = [w for w in executor_ws + loop_ws if not w.guarded]
            if not unguarded:
                continue
            w = min(executor_ws, key=lambda w: w.line)
            other = min(loop_ws, key=lambda w: w.line)
            findings.append(
                Finding(
                    path=relpath,
                    line=w.line,
                    code="TSA701",
                    message=(
                        f"attribute `{attr}` is assigned from an "
                        f"executor-submitted callable (`{w.fn_name}`, line "
                        f"{w.line}) AND from loop-side code (line "
                        f"{other.line}) without a lock on both sides; guard "
                        "both writes with a lock or use a thread-safe type"
                    ),
                    key=f"xthread:{attr}",
                )
            )

        # 3. nonlocal rebinding from executor callables (TSA702).
        for fn in executor_fns:
            if isinstance(fn, ast.Lambda):
                continue
            nonlocals: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Nonlocal):
                    nonlocals.update(node.names)
            if not nonlocals:
                continue
            assigned_here: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    tgts = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in tgts:
                        if isinstance(t, ast.Name) and t.id in nonlocals:
                            assigned_here.add(t.id)
            if not assigned_here:
                continue
            # The enclosing (loop-side) function: does it bind them too?
            encl = parents.get(fn)
            while encl is not None and not isinstance(
                encl, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                encl = parents.get(encl)
            if encl is None:
                continue
            for node in ast.walk(encl):
                if node is fn or not isinstance(
                    node, (ast.Assign, ast.AugAssign)
                ):
                    continue
                info = enclosing_info(node)
                if info["in_executor"] or info["guarded"]:
                    continue
                tgts = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in tgts:
                    if isinstance(t, ast.Name) and t.id in assigned_here:
                        findings.append(
                            Finding(
                                path=relpath,
                                line=fn.lineno,
                                code="TSA702",
                                message=(
                                    f"nonlocal `{t.id}` is rebound inside "
                                    f"executor-submitted `{fn.name}` and "
                                    "also assigned on the loop side (line "
                                    f"{node.lineno}) without a lock"
                                ),
                                key=f"nonlocal:{fn.name}:{t.id}",
                            )
                        )
                        assigned_here.discard(t.id)
    return findings
