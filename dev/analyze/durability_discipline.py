"""Pass 10 — durability discipline (TSA1001-TSA1004).

The lifecycle layer's crash-consistency story rests on ordering rules no
interpreter enforces: temp-write→``os.replace`` (or a
``StorageWriteStream`` commit) is THE commit point for every durable
object; the catalog record — the publish — lands only after
``.snapshot_metadata`` — the data commit; GC deletes only what a keep-set
membership check excluded; and every commit point stays reachable by a
``faults.py`` kill-point so chaos schedules can crash exactly there. This
pass makes each rule a gate (``dev/crash_explorer.py`` is its runtime
cross-check):

- **TSA1001** — a persistent-state mutation bypassing the atomic-commit
  idiom: a write-mode ``open()`` whose target is not a temp path and is
  never ``os.replace``d into place within the same function. Temp-write→
  rename, plugin-routed writes, and documented fail-open sidecars
  (``# noqa: TSA1001`` + rationale) stay quiet.
- **TSA1002** — publish-before-payload: a catalog/step-telemetry append
  reachable on a CFG path not dominated by the corresponding
  ``_write_snapshot_metadata`` data commit (``core.FlowWalker``).
- **TSA1003** — a delete issued from GC/retention/eviction code
  (function name matching ``gc``/``evict``/``retain``) with no preceding
  keep-set/pin membership check anywhere in the function.
- **TSA1004** — crash-surface drift: every function performing a direct
  durable mutation (``os.replace``/``rename``/``link``/``remove``/
  ``unlink``, or a mutating call on a storage plugin) must be pinned in
  ``faults.py``'s ``_CRASH_SURFACE`` table to a kill-point op class in
  ``_OPS`` (or declared ``fail-open``), and every table entry must still
  name a discovered site — the commit-point inventory and the chaos
  surface can never silently diverge.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, FlowWalker, dotted_name

# ----------------------------------------------------------- shared helpers

_WRITE_MODES_RE = re.compile(r"^[wax]|\+")

# Publish calls (the catalog-visible side) -> the data commit that must
# dominate them on every CFG path.
_PUBLISH_TO_COMMIT: Tuple[Tuple[str, str], ...] = (
    ("_append_catalog_record", "_write_snapshot_metadata"),
    ("_append_step_telemetry_record", "_write_snapshot_metadata"),
)
_PUBLISH_NAMES = {p for p, _ in _PUBLISH_TO_COMMIT}
_COMMIT_NAMES = {c for _, c in _PUBLISH_TO_COMMIT}

_GC_SCOPE_RE = re.compile(r"(?:^|_)(?:gc|evict|eviction|retain|retention)")
_KEEP_NAME_RE = re.compile(r"keep|retain|pinned|\bpin\b", re.IGNORECASE)

# Direct filesystem mutations that constitute (or finish) a commit point.
_OS_MUTATIONS = {
    "os.replace", "os.rename", "os.link", "os.remove", "os.unlink",
}
# Mutating methods of the StoragePlugin surface; a call through a receiver
# whose name mentions storage/plugin is a plugin-routed durable mutation.
_PLUGIN_MUTATIONS = {"write", "sync_write", "delete", "write_stream", "link_in"}
_PLUGIN_RECEIVER_RE = re.compile(r"storage|plugin")

# Files exempt from the TSA1004 inventory: the injection machinery itself
# and the journal that merely observes effects.
_INVENTORY_EXEMPT_BASENAMES = {"faults.py", "effect_journal.py"}


def _call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def _last_attr(name: Optional[str]) -> Optional[str]:
    if name is None:
        return None
    return name.rsplit(".", 1)[-1]


def _top_level_functions(tree: ast.AST):
    """(qualname, function node) for every module-level function and every
    method of a module-level class — the granularity at which commit
    points are named. Nested defs stay inside their owner's subtree."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on real trees
        return ""


def _looks_temp(node: ast.AST) -> bool:
    """Whether an open() target expression names a temp path: a variable /
    attribute whose name mentions tmp, or any literal part containing
    '.tmp' (the `f"{path}.tmp.{pid}"` idiom)."""
    text = _expr_text(node).lower()
    return "tmp" in text


def _open_mode(call: ast.Call) -> Optional[str]:
    """The mode literal of an ``open()`` call, or None when unknowable
    statically (default "r" returns "r")."""
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    if len(call.args) >= 2:
        if isinstance(call.args[1], ast.Constant):
            return str(call.args[1].value)
        return None
    return "r"


# ------------------------------------------------------------------ TSA1001


def _tsa1001(ctx: AnalysisContext, relpath: str) -> List[Finding]:
    tree = ctx.tree(relpath)
    if tree is None:
        return []
    findings: List[Finding] = []
    for qualname, fn in _top_level_functions(tree):
        # Names os.replace()d into place anywhere in this function: a
        # write to one is the temp leg of a temp->rename commit even when
        # the variable is not named like a temp.
        replaced: Set[str] = set()
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and _call_name(node) in ("os.replace", "os.rename")
                and node.args
            ):
                replaced.add(_expr_text(node.args[0]))
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "open"
                and node.args
            ):
                continue
            mode = _open_mode(node)
            if mode is not None and not _WRITE_MODES_RE.search(mode):
                continue
            target = node.args[0]
            if _looks_temp(target):
                continue
            if _expr_text(target) in replaced:
                continue
            findings.append(
                Finding(
                    path=relpath,
                    line=node.lineno,
                    code="TSA1001",
                    message=(
                        f"`{qualname}` opens `{_expr_text(target)}` for "
                        "writing in place: a crash mid-write leaves a torn "
                        "final object. Write a temp path and os.replace() "
                        "it in (or route through a StoragePlugin write); "
                        "a deliberately non-atomic fail-open sidecar needs "
                        "`# noqa: TSA1001` + a rationale"
                    ),
                    key=f"bare-open:{qualname}",
                )
            )
    return findings


# ------------------------------------------------------------------ TSA1002


class _PublishWalker(FlowWalker):
    """Token 'commit' is set by a data-commit call; a publish call in a
    state without it is reachable before the payload is durable."""

    def __init__(self, on_violation) -> None:
        self._on_violation = on_violation

    @staticmethod
    def _calls_in(stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                attr = _last_attr(_call_name(node))
                if attr is not None:
                    out.add(attr)
        return out

    def transfer(self, stmt: ast.stmt, state: frozenset) -> frozenset:
        calls = self._calls_in(stmt)
        if calls & _PUBLISH_NAMES and "commit" not in state:
            self._on_violation(stmt, sorted(calls & _PUBLISH_NAMES))
        if calls & _COMMIT_NAMES:
            return state | {"commit"}
        return state


def _tsa1002(ctx: AnalysisContext, relpath: str) -> List[Finding]:
    tree = ctx.tree(relpath)
    if tree is None:
        return []
    findings: List[Finding] = []
    for qualname, fn in _top_level_functions(tree):
        leaf = qualname.rsplit(".", 1)[-1]
        if leaf in _PUBLISH_NAMES:
            continue  # the publish implementation itself (and its callees)
        has_publish = any(
            isinstance(n, ast.Call)
            and _last_attr(_call_name(n)) in _PUBLISH_NAMES
            for n in ast.walk(fn)
        )
        if not has_publish:
            continue
        seen: Set[Tuple[int, str]] = set()

        def on_violation(stmt: ast.stmt, names: List[str]) -> None:
            for name in names:
                if (stmt.lineno, name) in seen:
                    continue
                seen.add((stmt.lineno, name))
                findings.append(
                    Finding(
                        path=relpath,
                        line=stmt.lineno,
                        code="TSA1002",
                        message=(
                            f"`{qualname}` publishes via `{name}` on a "
                            "path not dominated by the data commit "
                            "(`_write_snapshot_metadata`): a crash after "
                            "the publish leaves a catalog-visible record "
                            "for a snapshot that was never durable"
                        ),
                        key=f"publish-before-commit:{qualname}:{name}",
                    )
                )

        _PublishWalker(on_violation).walk(fn)
    return findings


# ------------------------------------------------------------------ TSA1003


def _is_delete_call(node: ast.Call) -> bool:
    name = _call_name(node)
    if name in ("os.remove", "os.unlink"):
        return True
    attr = _last_attr(name)
    return attr in ("delete", "delete_many")


def _tsa1003(ctx: AnalysisContext, relpath: str) -> List[Finding]:
    tree = ctx.tree(relpath)
    if tree is None:
        return []
    findings: List[Finding] = []
    for qualname, fn in _top_level_functions(tree):
        leaf = qualname.rsplit(".", 1)[-1].lower()
        if not _GC_SCOPE_RE.search(leaf):
            continue
        # Keep-set membership checks: `x (not) in <keep-ish>` compares
        # anywhere in the function (nested closures included — GC fans its
        # pre-filtered waves out through them).
        guard_lines = [
            node.lineno
            for node in ast.walk(fn)
            if isinstance(node, ast.Compare)
            and any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops)
            and _KEEP_NAME_RE.search(_expr_text(node))
        ]
        first_guard = min(guard_lines) if guard_lines else None
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and _is_delete_call(node)):
                continue
            if first_guard is not None and first_guard <= node.lineno:
                continue
            findings.append(
                Finding(
                    path=relpath,
                    line=node.lineno,
                    code="TSA1003",
                    message=(
                        f"GC-scope function `{qualname}` deletes with no "
                        "preceding keep-set/pin membership check: nothing "
                        "bounds what this sweep can destroy — filter the "
                        "victims through the keep-set (`p not in keep`) "
                        "or a pin check first"
                    ),
                    key=f"ungated-delete:{qualname}",
                )
            )
            break  # one finding per function
    return findings


# ------------------------------------------------------------------ TSA1004


def _pair_tuple(
    tree: ast.AST, var: str
) -> Optional[List[Tuple[str, str, int]]]:
    """[(site, op, line)] of a module-level ``var = (("a", "b"), ...)``."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == var for t in node.targets
        ):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            return None
        out = []
        for elt in node.value.elts:
            if (
                isinstance(elt, (ast.Tuple, ast.List))
                and len(elt.elts) == 2
                and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in elt.elts
                )
            ):
                out.append(
                    (elt.elts[0].value, elt.elts[1].value, elt.lineno)
                )
        return out
    return None


def discover_commit_points(
    ctx: AnalysisContext,
) -> Dict[str, Tuple[str, int]]:
    """The commit-point inventory: ``{site: (relpath, line)}`` where site is
    ``<basename>:<qualname>`` of every function performing a direct
    durable mutation. The reviewable mirror lives in ``faults.py``'s
    ``_CRASH_SURFACE``; :func:`run` pins the two to each other."""
    inventory: Dict[str, Tuple[str, int]] = {}
    for relpath in ctx.lib_files:
        base = os.path.basename(relpath)
        if base in _INVENTORY_EXEMPT_BASENAMES:
            continue
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for qualname, fn in _top_level_functions(tree):
            line = None
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if name in _OS_MUTATIONS:
                    line = node.lineno
                    break
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _PLUGIN_MUTATIONS
                    and _PLUGIN_RECEIVER_RE.search(
                        _expr_text(node.func.value).lower()
                    )
                ):
                    line = node.lineno
                    break
            if line is not None:
                inventory[f"{base}:{qualname}"] = (relpath, line)
    return inventory


def _tsa1004(ctx: AnalysisContext) -> List[Finding]:
    if ctx.faults_path is None:
        return []
    faults_tree = ctx.tree(ctx.faults_path)
    if faults_tree is None:
        return []
    from .fault_coverage import _string_tuple

    ops = (_string_tuple(faults_tree, "_OPS") or set()) | {"fail-open"}
    surface = _pair_tuple(faults_tree, "_CRASH_SURFACE")
    inventory = discover_commit_points(ctx)
    findings: List[Finding] = []
    if surface is None:
        if inventory:
            findings.append(
                Finding(
                    path=ctx.faults_path,
                    line=1,
                    code="TSA1004",
                    message=(
                        "faults.py has no _CRASH_SURFACE table: "
                        f"{len(inventory)} discovered commit-point "
                        "function(s) are unpinned from the kill-point op "
                        "classes"
                    ),
                    key="no-crash-surface",
                )
            )
        return findings
    pinned = {site: (op, line) for site, op, line in surface}
    for site, (relpath, line) in sorted(inventory.items()):
        if site not in pinned:
            findings.append(
                Finding(
                    path=relpath,
                    line=line,
                    code="TSA1004",
                    message=(
                        f"commit-point function `{site}` is not pinned in "
                        "faults.py _CRASH_SURFACE: chaos schedules cannot "
                        "prove a crash here is survivable — map it to a "
                        "kill-point op class (or declare it fail-open)"
                    ),
                    key=f"unpinned:{site}",
                )
            )
    for site, op, line in surface:
        if site not in inventory:
            findings.append(
                Finding(
                    path=ctx.faults_path,
                    line=line,
                    code="TSA1004",
                    message=(
                        f"_CRASH_SURFACE entry `{site}` matches no "
                        "discovered commit-point function (renamed or "
                        "removed?) — stale entries hide real drift"
                    ),
                    key=f"stale:{site}",
                )
            )
        if op not in ops:
            findings.append(
                Finding(
                    path=ctx.faults_path,
                    line=line,
                    code="TSA1004",
                    message=(
                        f"_CRASH_SURFACE pins `{site}` to op class "
                        f"`{op}`, which is not in _OPS (nor `fail-open`): "
                        "no kill-point rule can ever reach it"
                    ),
                    key=f"badop:{site}:{op}",
                )
            )
    return findings


# --------------------------------------------------------------------- run


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.lib_files:
        findings.extend(_tsa1001(ctx, relpath))
        findings.extend(_tsa1002(ctx, relpath))
        findings.extend(_tsa1003(ctx, relpath))
    findings.extend(_tsa1004(ctx))
    return findings
