"""Pass 5 — manifest schema discipline (TSA501).

``manifest.py``'s ``Entry`` subclasses ARE the on-storage metadata schema:
every field must serialize to the committed JSON document and round-trip
through ``Entry.from_dict``. A field annotated with a non-serializable type
(an ndarray, a callable, an arbitrary object) either crashes the commit or
— worse — pickles its repr and corrupts restores on the other side. This
pass checks that every annotated field of every Entry subclass is built
from serializable atoms: primitives, typing containers, and other schema
classes defined in the same module.

Code: **TSA501** — Entry-subclass field annotation uses a type outside the
serializable grammar.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import AnalysisContext, Finding

_ALLOWED_NAMES = {
    "str",
    "int",
    "float",
    "bool",
    "bytes",
    "None",
    "NoneType",
    "Any",
    "List",
    "Dict",
    "Tuple",
    "Optional",
    "Union",
    "Sequence",
    "Mapping",
    "list",
    "dict",
    "tuple",
}

_ROOT_CLASS = "Entry"


def _module_classes(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    return {
        node.name: node
        for node in tree.body
        if isinstance(node, ast.ClassDef)
    }


def _entry_subclasses(classes: Dict[str, ast.ClassDef]) -> List[ast.ClassDef]:
    """Entry + everything transitively inheriting it (within the module)."""
    members: Set[str] = set()
    if _ROOT_CLASS in classes:
        members.add(_ROOT_CLASS)
    changed = True
    while changed:
        changed = False
        for name, node in classes.items():
            if name in members:
                continue
            for base in node.bases:
                if isinstance(base, ast.Name) and base.id in members:
                    members.add(name)
                    changed = True
    return [classes[n] for n in sorted(members)]


def _bad_atom(node: ast.AST, allowed: Set[str]) -> Optional[str]:
    """First disallowed type atom in an annotation expression, or None."""
    if isinstance(node, ast.Name):
        return None if node.id in allowed else node.id
    if isinstance(node, ast.Attribute):
        # typing.List / np.ndarray: judge by the final attribute.
        return None if node.attr in allowed else ast.unparse(node)
    if isinstance(node, ast.Constant):
        if node.value is None or isinstance(node.value, type(Ellipsis)):
            return None
        if isinstance(node.value, str):
            # Forward reference: parse and recurse.
            try:
                inner = ast.parse(node.value, mode="eval").body
            except SyntaxError:
                return node.value
            return _bad_atom(inner, allowed)
        return repr(node.value)
    if isinstance(node, ast.Subscript):
        bad = _bad_atom(node.value, allowed)
        if bad is not None:
            return bad
        return _bad_atom(node.slice, allowed)
    if isinstance(node, ast.Tuple):
        for elt in node.elts:
            bad = _bad_atom(elt, allowed)
            if bad is not None:
                return bad
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _bad_atom(node.left, allowed) or _bad_atom(node.right, allowed)
    if isinstance(node, ast.Index):  # pragma: no cover - py<3.9 AST
        return _bad_atom(node.value, allowed)
    return ast.unparse(node) if hasattr(ast, "unparse") else "<complex>"


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    if ctx.manifest_path is None:
        return findings
    tree = ctx.tree(ctx.manifest_path)
    if tree is None or not isinstance(tree, ast.Module):
        return findings
    classes = _module_classes(tree)
    # Schema classes defined alongside Entry (Shard descriptors etc.) are
    # serializable by the same contract, so they are allowed atoms.
    allowed = _ALLOWED_NAMES | set(classes.keys())
    for cls in _entry_subclasses(classes):
        for stmt in cls.body:
            if not isinstance(stmt, ast.AnnAssign):
                continue
            bad = _bad_atom(stmt.annotation, allowed)
            if bad is not None:
                field = (
                    stmt.target.id
                    if isinstance(stmt.target, ast.Name)
                    else "<field>"
                )
                findings.append(
                    Finding(
                        path=ctx.manifest_path,
                        line=stmt.lineno,
                        code="TSA501",
                        message=(
                            f"`{cls.name}.{field}` annotation uses "
                            f"non-serializable type `{bad}`; manifest "
                            "entries must round-trip through the committed "
                            "JSON document"
                        ),
                        key=f"{cls.name}.{field}",
                    )
                )
    return findings
