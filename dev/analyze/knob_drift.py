"""Pass 3 — knob-registry drift (TSA301-TSA303).

Every ``TORCHSNAPSHOT_TPU_*`` environment knob has exactly one home:
``utils/knobs.py`` defines it (so overrides, defaults, and local-world
scaling live in one place) and the docs catalog (``docs/utilities.md``)
documents it. Anything else is drift: a literal elsewhere in the library
bypasses the registry's context-manager overrides; an undocumented knob is
invisible to operators; a documented-but-deleted knob is a lie.

Codes:

- **TSA301** — ``TORCHSNAPSHOT_TPU_*`` string literal in library code
  outside the knob registry (route the read/write through ``utils/knobs``).
- **TSA302** — registry knob missing from the docs catalog.
- **TSA303** — a doc mentions a ``TORCHSNAPSHOT_TPU_*`` name that no longer
  exists in the registry (dead documentation).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from .core import AnalysisContext, Finding

_KNOB_FULL_RE = re.compile(r"^TORCHSNAPSHOT_TPU_[A-Z0-9_]+$")
_KNOB_TOKEN_RE = re.compile(r"TORCHSNAPSHOT_TPU_[A-Z0-9_]+")


def registry_knobs(ctx: AnalysisContext) -> Dict[str, int]:
    """{env name: first definition line} from the knob registry module."""
    out: Dict[str, int] = {}
    if ctx.knobs_path is None:
        return out
    tree = ctx.tree(ctx.knobs_path)
    if tree is None:
        return out
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _KNOB_FULL_RE.match(node.value)
        ):
            out.setdefault(node.value, node.lineno)
    return out


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    registry = registry_knobs(ctx)

    # TSA301: literals in library code outside the registry.
    for relpath in ctx.lib_files:
        if relpath == ctx.knobs_path:
            continue
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _KNOB_FULL_RE.match(node.value)
            ):
                findings.append(
                    Finding(
                        path=relpath,
                        line=node.lineno,
                        code="TSA301",
                        message=(
                            f"knob literal `{node.value}` outside the "
                            "registry; add it to utils/knobs.py and call "
                            "the getter here"
                        ),
                        key=node.value,
                    )
                )

    # TSA302: registry knob absent from the docs catalog.
    if ctx.knobs_path is not None and ctx.catalog_path is not None:
        catalog_text = ctx.source(ctx.catalog_path)
        catalog_names = set(_KNOB_TOKEN_RE.findall(catalog_text))
        for env_name, lineno in sorted(registry.items()):
            if env_name not in catalog_names:
                findings.append(
                    Finding(
                        path=ctx.knobs_path,
                        line=lineno,
                        code="TSA302",
                        message=(
                            f"knob `{env_name}` is not documented in "
                            f"{ctx.catalog_path}"
                        ),
                        key=env_name,
                    )
                )

    # TSA303: documented knob that no longer exists.
    if registry:
        for doc in ctx.doc_files:
            text = ctx.source(doc)
            for i, line in enumerate(text.split("\n"), 1):
                for token in _KNOB_TOKEN_RE.findall(line):
                    if token not in registry:
                        findings.append(
                            Finding(
                                path=doc,
                                line=i,
                                code="TSA303",
                                message=(
                                    f"documented knob `{token}` does not "
                                    "exist in utils/knobs.py (dead catalog "
                                    "entry?)"
                                ),
                                key=token,
                            )
                        )
    return findings
