"""Pass 6 — flow-sensitive resource balance (TSA601/TSA602).

The memory-budget ledger is the invariant the whole pipeline design rests
on: every ``budget.debit(...)`` (request admission, per-chunk streaming
accounting) and every ``lanes.try_admit(...)`` (D2H look-ahead window
reservation) must be matched — on EVERY path, including exception paths and
early returns — by a credit/release, or handed to an owner that guarantees
the release (the task tables ``_reap``/``_abort_inflight`` sweep, a
look-ahead deque the stream's cleanup drains, an ``outstanding`` counter a
``finally`` credits). The two bugs this class actually produced (PR 5:
failed staging tasks kept their reservation; PR 6: aborted streams stranded
lane-window admissions until a ``release_all`` sweep was added) were both
invisible to the earlier passes — they are *flow* bugs, not call-shape bugs.

Each function containing an acquisition is walked with the
:class:`~dev.analyze.core.FlowWalker` engine, tracking the set of open
acquisitions per path. An acquisition is closed by:

- a release call (``.credit(X)`` / ``.release(X)`` matches the acquisition
  with the same amount expression, else the most recent one;
  ``.release_all()`` closes every open window admission);
- a **handoff** that transfers ownership to a releasing owner: the amount
  (or a value it was derived from) is stored into a container
  (``tasks[t] = (req, cost, ...)``), appended/put onto one
  (``pending.append((fut, est))``), accumulated into a ledger counter
  (``outstanding += nbytes``), or returned to the caller.

Codes:

- **TSA601** — a path exits the function (early return, fall-through, or an
  unprotected raising statement) with an acquisition still open: the
  reservation leaks. A try whose handler/finally credits/releases protects
  its body's exceptional paths.
- **TSA602** — an ``await`` point while an acquisition is open and no
  protecting try encloses it: cancellation at that suspension strands the
  reservation even if the happy path balances (the PR 5 ``_reap`` shape).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, FlowWalker, dotted_name, iter_functions

_ACQUIRE_DEBIT = "debit"
_ACQUIRE_ADMIT = "try_admit"
_RELEASES = ("credit", "release")
_RELEASE_ALL = "release_all"
_HANDOFF_METHODS = {
    "append", "appendleft", "add", "put", "put_nowait", "extend",
}


def _last_attr(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    if name is not None:
        return name.rsplit(".", 1)[-1]
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _amount_expr(call: ast.Call) -> Optional[ast.expr]:
    return call.args[0] if call.args else None


def _names_in(node: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


class _Token:
    """One open acquisition, value-equal by (kind, site line, amount): the
    same site re-acquired on another loop pass is the same token, so loop
    states converge."""

    __slots__ = ("kind", "line", "amount_dump", "amount_names")

    def __init__(self, kind: str, call: ast.Call) -> None:
        self.kind = kind
        self.line = call.lineno
        amount = _amount_expr(call)
        self.amount_dump = ast.dump(amount) if amount is not None else ""
        self.amount_names = (
            frozenset(_names_in(amount)) if amount is not None else frozenset()
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, _Token)
            and self.kind == other.kind
            and self.line == other.line
            and self.amount_dump == other.amount_dump
        )

    def __hash__(self) -> int:
        return hash((self.kind, self.line, self.amount_dump))


class _BalanceWalker(FlowWalker):
    def __init__(self, relpath: str, fn, derived: Dict[str, Set[str]]) -> None:
        self.relpath = relpath
        self.fn = fn
        # name -> names it was assigned from (one level): lets a handoff of
        # `buf` close a debit of `nbytes` when `nbytes = memoryview(buf).nbytes`.
        self.derived = derived
        self.findings: Dict[Tuple[int, str], Finding] = {}

    # -- token bookkeeping --------------------------------------------------
    def _token_matches_names(self, token: _Token, names: Set[str]) -> bool:
        if token.amount_names & names:
            return True
        for n in token.amount_names:
            if self.derived.get(n, set()) & names:
                return True
        return False

    def _close_release(self, state: Set[_Token], call: ast.Call) -> Set[_Token]:
        attr = _last_attr(call)
        if attr == _RELEASE_ALL:
            return {t for t in state if t.kind != _ACQUIRE_ADMIT}
        amount = _amount_expr(call)
        dump = ast.dump(amount) if amount is not None else None
        exact = [t for t in state if dump is not None and t.amount_dump == dump]
        if exact:
            victim = max(exact, key=lambda t: t.line)
            return state - {victim}
        if state:
            # No amount match (aggregated credit like `credit(outstanding)`):
            # release the most recent open acquisition.
            victim = max(state, key=lambda t: t.line)
            return state - {victim}
        return state

    def _apply_handoffs(self, stmt: ast.stmt, state: Set[_Token]) -> Set[_Token]:
        if not state:
            return state
        closed: Set[_Token] = set()
        if isinstance(stmt, ast.Assign):
            if any(
                isinstance(t, (ast.Subscript, ast.Attribute))
                for t in stmt.targets
            ):
                names = _names_in(stmt.value)
                closed |= {
                    t for t in state if self._token_matches_names(t, names)
                }
        elif isinstance(stmt, ast.AugAssign):
            names = _names_in(stmt.value)
            closed |= {t for t in state if self._token_matches_names(t, names)}
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            names = _names_in(stmt.value)
            closed |= {t for t in state if self._token_matches_names(t, names)}
        for call in (
            n for n in ast.walk(stmt) if isinstance(n, ast.Call)
        ):
            attr = _last_attr(call)
            if attr in _HANDOFF_METHODS:
                names = set()
                for arg in call.args:
                    names |= _names_in(arg)
                closed |= {
                    t for t in state if self._token_matches_names(t, names)
                }
        return state - closed

    # -- FlowWalker hooks ---------------------------------------------------
    def transfer(self, stmt: ast.stmt, state: frozenset) -> frozenset:
        out: Set[_Token] = set(state)
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            attr = _last_attr(node)
            if attr == _ACQUIRE_DEBIT:
                out.add(_Token(_ACQUIRE_DEBIT, node))
            elif attr == _ACQUIRE_ADMIT:
                # Unconditional-form admission (the conditional `if not
                # lanes.try_admit(...)` form is handled in branch()).
                out.add(_Token(_ACQUIRE_ADMIT, node))
            elif attr in _RELEASES or attr == _RELEASE_ALL:
                out = self._close_release(out, node)
        out = self._apply_handoffs(stmt, out)
        return frozenset(out)

    def branch(self, test: ast.expr, state: frozenset):
        # `if X.try_admit(...):` → admitted on the true side only;
        # `if not X.try_admit(...):` → admitted on the FALSE side only
        # (the true side typically breaks/returns without a reservation).
        call, negated = None, False
        expr = test
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            negated = True
            expr = expr.operand
        if isinstance(expr, ast.Call) and _last_attr(expr) == _ACQUIRE_ADMIT:
            call = expr
        if call is None:
            return {state}, {state}
        admitted = frozenset(set(state) | {_Token(_ACQUIRE_ADMIT, call)})
        if negated:
            return {state}, {admitted}
        return {admitted}, {state}

    def try_protects(self, trystmt: ast.Try) -> bool:
        bodies = list(trystmt.finalbody)
        for handler in trystmt.handlers:
            bodies.extend(handler.body)
        for stmt in bodies:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                if isinstance(node, ast.Call):
                    attr = _last_attr(node)
                    if attr in _RELEASES or attr == _RELEASE_ALL:
                        return True
        return False

    def may_raise(self, stmt: ast.stmt) -> bool:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, (ast.Call, ast.Raise)):
                return True
        return False

    # -- reporting ----------------------------------------------------------
    def _verb(self, token: _Token) -> str:
        if token.kind == _ACQUIRE_ADMIT:
            return "window admission (try_admit)"
        return "budget debit"

    def _report(self, code: str, line: int, token: _Token, why: str) -> None:
        key = (token.line, code)
        if key in self.findings:
            return
        self.findings[key] = Finding(
            path=self.relpath,
            line=token.line,
            code=code,
            message=(
                f"{self._verb(token)} in `{self.fn.name}` (line {token.line}) "
                f"{why} — credit/release it, protect it with a try/finally, "
                "or hand it to an owning container/counter that releases it"
            ),
            key=f"{self.fn.name}:{token.kind}:{token.line - self.fn.lineno}",
        )

    def on_await(self, stmt: ast.stmt, state: frozenset) -> None:
        for token in state:
            self._report(
                "TSA602",
                stmt.lineno,
                token,
                f"is open across the await at line {stmt.lineno}; "
                "cancellation there strands the reservation",
            )

    def on_unprotected_raise(self, stmt: ast.stmt, state: frozenset) -> None:
        for token in state:
            self._report(
                "TSA601",
                stmt.lineno,
                token,
                f"leaks if line {stmt.lineno} raises "
                "(no protecting try/finally encloses it)",
            )

    def on_exit(self, node: ast.AST, state: frozenset, how: str) -> None:
        where = (
            f"the return at line {node.lineno}"
            if how == "return"
            else "the end of the function"
        )
        for token in state:
            self._report(
                "TSA601", getattr(node, "lineno", token.line), token,
                f"is still open at {where}",
            )


def _derivations(fn) -> Dict[str, Set[str]]:
    """name -> names appearing in its (single-target) assignments, one
    level deep — enough to tie `nbytes = memoryview(buf).nbytes` to `buf`."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            continue
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                out.setdefault(tgt.id, set()).update(_names_in(node.value))
    return out


def _own_body_nodes(fn):
    """Nodes of ``fn``'s own body, stopping at nested function boundaries
    (nested defs are walked as their own functions)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.lib_files:
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for fn in iter_functions(tree):
            has = any(
                isinstance(n, ast.Call)
                and _last_attr(n) in (_ACQUIRE_DEBIT, _ACQUIRE_ADMIT)
                for n in _own_body_nodes(fn)
            )
            if not has:
                continue
            walker = _BalanceWalker(relpath, fn, _derivations(fn))
            walker.walk(fn)
            findings.extend(walker.findings.values())
    return findings
