"""Pass 9 — collective discipline (TSA901-TSA904), flow-aware.

Every cross-rank protocol in the library — commit/restore barriers, the
plan-cache preflight broadcast, broadcast restore, the reshard read plans —
rests on one invariant enforced nowhere by the interpreter: *collective call
sequences must be SPMD-pure*, identical on every rank. One divergent rank
deadlocks the fleet (a peer waits on a store key nobody posts) or corrupts
it (a broadcast consumed against the wrong generation's namespace). The
hazards are flow bugs, invisible to call-shape passes: a collective behind a
rank-derived branch, a barrier added only in an ``except`` handler, a loop
whose trip count differs per rank issuing a collective per pass.

The **collective surface** this pass models:

- coordinator collectives: ``barrier``, ``all_gather_object``,
  ``broadcast_object``, ``gather_object``, ``scatter_object``;
- :class:`LinearBarrier` phases: ``arrive`` / ``depart``;
- coordinator-store ops (``set``/``get``/``try_get``/``add``/``delete``)
  when issued on a store-named receiver (``store``/``_store``/``ns``/…) —
  the generation-token get/set/increment traffic the collectives ride;
- ``defer_delete`` (store-key GC registration).

``report_error`` and ``note_external_barrier`` are *not* surface: the first
is the sanctioned error fan-out (asymmetric by contract), the second is
local bookkeeping. The protocol-implementing modules
(``parallel/coordinator.py``, ``parallel/store.py``) are exempt — rank
asymmetry there IS the protocol (a broadcast source sets where a sink gets).

**Divergence taint**: a branch predicate or loop bound is locally divergent
when it derives (transitively, through single-target assignments) from rank
identity (``rank``/``*_rank``/``get_rank()``/``process_index``), wall-clock
reads (``time.monotonic()``/…), local filesystem state
(``os.path.*``/``listdir``/``exists``/``stat``), randomness
(``random``/``uuid``/``os.urandom``), a caught-exception name, or a
``gather_object`` result (None on every non-destination rank). Manifest-,
knob-, and broadcast-derived state is untouched: collectives driven by those
are the sanctioned SPMD idiom.

Codes:

- **TSA901** — a collective reachable only under a divergence-tainted
  branch, with no matching collective on the sibling path: the ranks that
  take the other side never issue it.
- **TSA902** — a collective lexically inside an ``except`` handler or
  ``finally`` body: peers on the happy path never reach it, so the handler
  trades one failure for a fleet-wide hang.
- **TSA903** — a loop whose iteration count is divergence-tainted issuing a
  collective per iteration: ranks fall out of lockstep after the first
  extra pass.
- **TSA904** — SPMD purity of *plan-affecting* functions (broadcast
  eligibility, read-plan construction, reshard overlap planning — pinned in
  :data:`_SPMD_PURE_FUNCS`, extendable with a ``# spmd-pure`` marker on the
  ``def`` line): any read of non-(manifest|knob|entry) state — wall clock,
  local filesystem, environment outside the knob registry, randomness,
  rank identity, memory-budget probes — inside them is a finding, because
  their outputs feed byte-identical (path, range) plans on every rank.

The runtime cross-check is the collective lockstep sanitizer
(``TORCHSNAPSHOT_TPU_DEBUG_COLLECTIVES=1``, ``collective_tracer.py``): this
pass proves lockstep over the CFG, the tracer proves it over executions, and
CI runs both.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, dotted_name, iter_functions

# Files implementing the collective protocol itself: rank-asymmetric store
# traffic there is the protocol, not a divergence hazard (the lockstep
# tracer's own cross-check exchange included — it runs strictly after a
# barrier every rank passed).
_IMPL_EXEMPT_SUFFIXES = (
    "parallel/coordinator.py",
    "parallel/store.py",
    "collective_tracer.py",
    # The fleet telemetry bus is diagnostics-plane store traffic by design:
    # per-rank beacon keys are written asymmetrically (each rank its own,
    # readers read all) — the same sanctioned asymmetry as report_error.
    "telemetry/fleet.py",
)

_COLLECTIVE_ATTRS = {
    "barrier",
    "all_gather_object",
    "broadcast_object",
    "gather_object",
    "scatter_object",
    "arrive",
    "depart",
    "defer_delete",
}

_STORE_OPS = {"set", "get", "try_get", "add", "delete"}
_STORE_RECEIVERS = {"store", "_store", "ns", "_ns", "kvstore"}

# Plan-affecting functions pinned to SPMD purity (TSA904): their outputs
# must be identical on every rank because peers plan broadcast sequences /
# read requests from them. (file suffix, function name).
_SPMD_PURE_FUNCS: Tuple[Tuple[str, str], ...] = (
    ("bcast.py", "eligible"),
    ("bcast.py", "elect_reader"),
    ("bcast.py", "reader_order"),
    ("bcast.py", "is_fully_replicated_target"),
    ("snapshot.py", "_prepare_restore_one"),
    ("io_preparers/sharded_array.py", "overlap"),
    ("io_preparers/sharded_array.py", "subdivide"),
    ("io_preparers/sharded_array.py", "prepare_read"),
    ("io_preparers/array.py", "prepare_read"),
    ("io_preparers/chunked_array.py", "prepare_read"),
    ("io_preparers/object.py", "prepare_read"),
)

_TIME_CALLS = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.time_ns",
    "time.monotonic_ns",
    "datetime.now",
    "datetime.datetime.now",
}
_FS_CALLS = {"os.stat", "os.listdir", "os.scandir", "os.walk", "os.access", "glob.glob"}
_FS_ATTRS = {"exists", "is_file", "is_dir", "isfile", "isdir", "listdir", "scandir"}
_RANDOM_PREFIXES = ("random.", "uuid.")
_RANK_CALL_ATTRS = {"get_rank", "process_index"}

# Impure sources inside SPMD-pure (TSA904) functions. Knob getters
# (``knobs.*``) are explicitly legal: knobs are part of the plan's declared
# input surface (identical across a correctly-launched fleet).
_IMPURE_CALL_PREFIXES = (
    "time.",
    "random.",
    "uuid.",
    "socket.",
    "platform.",
    "psutil.",
    "os.",
)
_IMPURE_BARE_CALLS = {"open", "input"}
_IMPURE_CALL_ATTRS = _FS_ATTRS | {
    "monotonic",
    "perf_counter",
    "urandom",
    "gethostname",
    "getpid",
    "virtual_memory",
}
_IMPURE_NAME_MARKERS = ("memory_budget", "available_memory")


def _last_attr(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    if name is not None:
        return name.rsplit(".", 1)[-1]
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _receiver_parts(func: ast.AST) -> Set[str]:
    """Identifier parts of the receiver chain of ``a.b.c.op`` → {a, b, c}."""
    parts: Set[str] = set()
    node = func
    if isinstance(node, ast.Attribute):
        node = node.value  # drop the op itself
    while isinstance(node, ast.Attribute):
        parts.add(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.add(node.id)
    return parts


def collective_op(call: ast.Call) -> Optional[str]:
    """Canonical surface-op label for a call, or None."""
    attr = _last_attr(call)
    if attr is None:
        return None
    if attr in _COLLECTIVE_ATTRS:
        return attr
    if attr in _STORE_OPS and (_receiver_parts(call.func) & _STORE_RECEIVERS):
        return f"store.{attr}"
    return None


def _rankish(name: str) -> bool:
    return (
        name in ("rank", "process_index")
        or name.endswith("_rank")
        or name.startswith("rank_")
    )


def _own_body_nodes(stmts):
    """Source-ordered nodes of ``stmts``, stopping at nested function/class
    boundaries (nested defs are analyzed as their own functions)."""
    for stmt in stmts:
        stack = [stmt]
        while stack:
            node = stack.pop(0)
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
            ):
                continue
            yield node
            stack[:0] = list(ast.iter_child_nodes(node))


def _names_outside_call_args(expr: ast.AST) -> Set[str]:
    """Load-context names in ``expr``, NOT descending into call arguments:
    ``is_leader = rank == 0`` ties ``is_leader`` to ``rank``, but
    ``barrier = LinearBarrier(rank=rank, ...)`` does not taint ``barrier``
    — an object *parameterized* by rank is not itself a divergent value
    (branching on ``barrier is not None`` is a world-size gate, the
    library's pervasive idiom). Divergent call RESULTS are caught by the
    base-call taint (``get_rank()``, ``time.monotonic()``, …) instead."""
    out: Set[str] = set()
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            stack.append(node.func)
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _collectives_in(stmts) -> List[Tuple[str, ast.Call]]:
    out = []
    for node in _own_body_nodes(stmts):
        if isinstance(node, ast.Call):
            op = collective_op(node)
            if op is not None:
                out.append((op, node))
    out.sort(key=lambda t: (t[1].lineno, t[1].col_offset))
    return out


class _Taint:
    """Divergence taint over one function: base-tainted expressions plus a
    transitive closure over single-target assignments."""

    def __init__(self, fn) -> None:
        self.fn = fn
        # except-handler bound names: caught-exception identity.
        self.exc_names: Set[str] = set()
        for node in _own_body_nodes(fn.body):
            if isinstance(node, ast.ExceptHandler) and node.name:
                self.exc_names.add(node.name)
        # name -> names its assignment reads (one level).
        derived: Dict[str, Set[str]] = {}
        # names whose assignment expression is base-tainted via a call.
        tainted: Dict[str, str] = {}
        for node in _own_body_nodes(fn.body):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    derived.setdefault(tgt.id, set()).update(
                        _names_outside_call_args(node.value)
                    )
                    reason = self._expr_base_reason(node.value)
                    if reason is not None:
                        tainted.setdefault(tgt.id, reason)
        # Fixpoint: a name is tainted if any name it derives from is.
        changed = True
        while changed:
            changed = False
            for name, srcs in derived.items():
                if name in tainted:
                    continue
                for src in srcs:
                    if src in tainted:
                        tainted[name] = tainted[src]
                        changed = True
                        break
                    if _rankish(src):
                        tainted[name] = f"rank identity (`{src}`)"
                        changed = True
                        break
                    if src in self.exc_names:
                        tainted[name] = f"caught-exception identity (`{src}`)"
                        changed = True
                        break
        self.tainted_names = tainted

    def _call_reason(self, call: ast.Call) -> Optional[str]:
        name = dotted_name(call.func) or ""
        attr = _last_attr(call)
        if attr in _RANK_CALL_ATTRS:
            return f"rank identity (`{name or attr}()`)"
        if name in _TIME_CALLS or attr in ("monotonic", "perf_counter"):
            return f"wall-clock time (`{name or attr}()`)"
        if (
            name in _FS_CALLS
            or name.startswith("os.path.")
            or attr in _FS_ATTRS
        ):
            return f"local filesystem state (`{name or attr}()`)"
        if name.startswith(_RANDOM_PREFIXES) or name == "os.urandom":
            return f"randomness (`{name}()`)"
        if attr == "gather_object":
            return "a gather_object result (None on non-destination ranks)"
        return None

    def _expr_base_reason(self, expr: ast.AST) -> Optional[str]:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                reason = self._call_reason(node)
                if reason is not None:
                    return reason
        return None

    def reason(self, expr: ast.AST) -> Optional[str]:
        """Why ``expr`` is locally divergent, or None."""
        base = self._expr_base_reason(expr)
        if base is not None:
            return base
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if _rankish(node.id):
                    return f"rank identity (`{node.id}`)"
                if node.id in self.exc_names:
                    return f"caught-exception identity (`{node.id}`)"
                if node.id in self.tainted_names:
                    return (
                        f"`{node.id}`, derived from "
                        f"{self.tainted_names[node.id]}"
                    )
            elif isinstance(node, ast.Attribute):
                if _rankish(node.attr):
                    return f"rank identity (`.{node.attr}`)"
        return None


def _fn_key(fn, node: ast.AST, code: str, op: str) -> str:
    return f"{fn.name}:{op}:{getattr(node, 'lineno', 0) - fn.lineno}"


def _check_branches(relpath, fn, taint, findings) -> None:
    for node in _own_body_nodes(fn.body):
        if not isinstance(node, ast.If):
            continue
        reason = taint.reason(node.test)
        if reason is None:
            continue
        body_ops = _collectives_in(node.body)
        else_ops = _collectives_in(node.orelse)
        if [op for op, _ in body_ops] == [op for op, _ in else_ops]:
            continue
        # Flag each collective not matched (by op multiset) on the sibling.
        body_counts = Counter(op for op, _ in body_ops)
        else_counts = Counter(op for op, _ in else_ops)
        for ops, counts, sibling in (
            (body_ops, body_counts - else_counts, "else"),
            (else_ops, else_counts - body_counts, "if"),
        ):
            remaining = dict(counts)
            for op, call in ops:
                if remaining.get(op, 0) <= 0:
                    continue
                remaining[op] -= 1
                findings.append(
                    Finding(
                        path=relpath,
                        line=call.lineno,
                        code="TSA901",
                        message=(
                            f"collective `{op}` in `{fn.name}` is reachable "
                            f"only under a locally-divergent condition (line "
                            f"{node.lineno} branches on {reason}) with no "
                            f"matching collective on the {sibling} path — "
                            "ranks taking the other side never issue it "
                            "(deadlock/desync); hoist it out of the branch "
                            "or mirror it on the sibling path"
                        ),
                        key=_fn_key(fn, call, "TSA901", op),
                    )
                )


def _check_handlers(relpath, fn, findings) -> None:
    for node in _own_body_nodes(fn.body):
        if not isinstance(node, ast.Try):
            continue
        regions = [
            (handler.body, "an `except` handler") for handler in node.handlers
        ]
        if node.finalbody:
            regions.append((node.finalbody, "a `finally` block"))
        for body, where in regions:
            for op, call in _collectives_in(body):
                findings.append(
                    Finding(
                        path=relpath,
                        line=call.lineno,
                        code="TSA902",
                        message=(
                            f"collective `{op}` in `{fn.name}` is issued "
                            f"inside {where} (try at line {node.lineno}) — "
                            "peers on the happy path never reach it, so the "
                            "handler trades one rank's failure for a "
                            "fleet-wide hang; report through "
                            "`report_error`/structured aborts instead, or "
                            "issue the collective on every path"
                        ),
                        key=_fn_key(fn, call, "TSA902", op),
                    )
                )


def _check_loops(relpath, fn, taint, findings) -> None:
    for node in _own_body_nodes(fn.body):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            bound, what = node.iter, "iterates over"
        elif isinstance(node, ast.While):
            if isinstance(node.test, ast.Constant):
                continue  # `while True` polling loops converge elsewhere
            bound, what = node.test, "is bounded by"
        else:
            continue
        reason = taint.reason(bound)
        if reason is None:
            continue
        for op, call in _collectives_in(node.body):
            findings.append(
                Finding(
                    path=relpath,
                    line=call.lineno,
                    code="TSA903",
                    message=(
                        f"collective `{op}` in `{fn.name}` is issued per "
                        f"iteration of the loop at line {node.lineno}, which "
                        f"{what} {reason} — the trip count can differ across "
                        "ranks, so peers fall out of lockstep after the "
                        "first extra pass; derive the bound from "
                        "manifest/knob/broadcast state or hoist the "
                        "collective out of the loop"
                    ),
                    key=_fn_key(fn, call, "TSA903", op),
                )
            )


def _spmd_pure_targets(ctx: AnalysisContext, relpath: str, tree) -> List[ast.AST]:
    lines = ctx.lines(relpath)
    out = []
    for fn in iter_functions(tree):
        pinned = any(
            relpath.endswith(suffix) and fn.name == name
            for suffix, name in _SPMD_PURE_FUNCS
        )
        marked = False
        if 1 <= fn.lineno <= len(lines) and "spmd-pure" in lines[fn.lineno - 1]:
            marked = True
        if pinned or marked:
            out.append(fn)
    return out


def _check_purity(relpath, fn, findings) -> None:
    for node in _own_body_nodes(fn.body):
        problem: Optional[str] = None
        line = getattr(node, "lineno", fn.lineno)
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            attr = _last_attr(node)
            if (
                name.startswith(_IMPURE_CALL_PREFIXES)
                or name in _IMPURE_BARE_CALLS
                or attr in _IMPURE_CALL_ATTRS
                or attr in _RANK_CALL_ATTRS
                or any(
                    marker in (name or attr or "")
                    for marker in _IMPURE_NAME_MARKERS
                )
            ):
                problem = f"call to `{name or attr}`"
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if _rankish(node.id):
                problem = f"read of rank identity `{node.id}`"
        if problem is not None:
            findings.append(
                Finding(
                    path=relpath,
                    line=line,
                    code="TSA904",
                    message=(
                        f"`{fn.name}` is SPMD-purity-pinned (its output "
                        "feeds rank-identical plans) but contains a "
                        f"{problem}: only manifest-entry, knob, and "
                        "argument-derived state is legal here — move the "
                        "impure read to the caller or drop the function "
                        "from the plan-affecting surface"
                    ),
                    key=f"{fn.name}:{problem}:{line - fn.lineno}",
                )
            )


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.lib_files:
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        exempt = relpath.endswith(_IMPL_EXEMPT_SUFFIXES)
        pure_targets = _spmd_pure_targets(ctx, relpath, tree)
        for fn in iter_functions(tree):
            if fn in pure_targets:
                _check_purity(relpath, fn, findings)
            if exempt:
                continue
            has = any(
                isinstance(n, ast.Call) and collective_op(n) is not None
                for n in _own_body_nodes(fn.body)
            )
            if not has:
                continue
            taint = _Taint(fn)
            _check_branches(relpath, fn, taint, findings)
            _check_handlers(relpath, fn, findings)
            _check_loops(relpath, fn, taint, findings)
    return findings
