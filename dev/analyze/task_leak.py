"""Pass 2 — task-leak (TSA201/TSA202).

Every ``asyncio.ensure_future``/``create_task`` in the pipelines follows the
scheduler's ``_reap`` pattern: the task is retained (dict key, list element,
gathered) and its ``.result()`` is eventually read, so failures propagate.
A discarded task object is garbage-collected mid-flight (Python cancels it)
and its exception is silently dropped — the classic asyncio leak.

Codes:

- **TSA201** — task-spawn result discarded (bare expression statement).
  Retain it and reap/await it, or chain ``.add_done_callback`` for a true
  fire-and-forget (chaining keeps the statement from being a bare spawn, so
  it is not flagged).
- **TSA202** — task-spawn result assigned to a name that is never read
  again in the enclosing scope: retained in name only, never reaped.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import AnalysisContext, Finding, dotted_name, parent_map

_SPAWN_NAMES = {"ensure_future", "create_task"}


def _is_spawn(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in _SPAWN_NAMES


def _enclosing_scope(node: ast.AST, parents) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            return cur
        cur = parents.get(cur)
    return None


def _name_is_read(scope: ast.AST, name: str, skip: ast.Assign) -> bool:
    for node in ast.walk(scope):
        if node is skip:
            continue
        if (
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Load)
        ):
            return True
        # `del task` after gathering counts as handling too.
        if (
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Del)
        ):
            return True
    return False


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.lib_files:
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        parents = parent_map(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_spawn(node)):
                continue
            parent = parents.get(node)
            spawn = dotted_name(node.func)
            if isinstance(parent, ast.Expr):
                findings.append(
                    Finding(
                        path=relpath,
                        line=node.lineno,
                        code="TSA201",
                        message=(
                            f"`{spawn}(...)` result discarded: the task can "
                            "be garbage-collected mid-flight and its "
                            "exception is lost; retain and reap/await it "
                            "(or chain .add_done_callback)"
                        ),
                        key=f"discard:{spawn}",
                    )
                )
                continue
            if isinstance(parent, ast.Assign):
                targets = [
                    t for t in parent.targets if isinstance(t, ast.Name)
                ]
                if len(targets) != len(parent.targets):
                    continue  # tuple/attr targets: assume retained
                scope = _enclosing_scope(node, parents)
                if scope is None:
                    continue
                for tgt in targets:
                    if not _name_is_read(scope, tgt.id, parent):
                        findings.append(
                            Finding(
                                path=relpath,
                                line=node.lineno,
                                code="TSA202",
                                message=(
                                    f"task assigned to `{tgt.id}` is never "
                                    "awaited/reaped in this scope; its "
                                    "failure would be silently dropped"
                                ),
                                key=f"leak:{tgt.id}",
                            )
                        )
    return findings
