"""Pass 2 — task-leak (TSA201-TSA204).

Every ``asyncio.ensure_future``/``create_task`` in the pipelines follows the
scheduler's ``_reap`` pattern: the task is retained (dict key, list element,
gathered) and its ``.result()`` is eventually read, so failures propagate.
A discarded task object is garbage-collected mid-flight (Python cancels it)
and its exception is silently dropped — the classic asyncio leak.

``executor.submit(...)`` futures leak the same way with worse symptoms: a
discarded ``concurrent.futures.Future`` is NOT cancelled by the GC — the
worker runs to completion, its exception is stored on a dead object, and
``ThreadPoolExecutor.shutdown`` happily waits for work nobody will ever
collect (the PR 5 ``_reap`` bug was exactly this shape on the budget side).

Codes:

- **TSA201** — task-spawn result discarded (bare expression statement).
  Retain it and reap/await it, or chain ``.add_done_callback`` for a true
  fire-and-forget (chaining keeps the statement from being a bare spawn, so
  it is not flagged).
- **TSA202** — task-spawn result assigned to a name that is never read
  again in the enclosing scope: retained in name only, never reaped.
- **TSA203** — ``*.submit(...)`` executor-future discarded (bare expression
  statement): its exception is silently dropped and error paths cannot
  cancel it.
- **TSA204** — ``*.submit(...)`` future assigned to a name never read again
  in the enclosing scope. Sanctioned collection idioms (``.result()``,
  ``asyncio.wrap_future``, ``as_completed``/``wait``, ``.cancel()`` on
  error paths) are all reads of the name, so they stay quiet.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import AnalysisContext, Finding, dotted_name

_SPAWN_NAMES = {"ensure_future", "create_task"}


def _spawn_kind(call: ast.Call) -> Optional[str]:
    """"task" for ensure_future/create_task, "future" for *.submit, else
    None. Bare ``submit`` names don't count — only method form, so unrelated
    helpers named submit stay quiet."""
    name = dotted_name(call.func)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if last in _SPAWN_NAMES:
        return "task"
    if last == "submit" and "." in name:
        return "future"
    return None


def _enclosing_scope(node: ast.AST, parents) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            return cur
        cur = parents.get(cur)
    return None


def _name_is_read(scope: ast.AST, name: str, skip: ast.Assign) -> bool:
    for node in ast.walk(scope):
        if node is skip:
            continue
        if (
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Load)
        ):
            return True
        # `del task` after gathering counts as handling too.
        if (
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Del)
        ):
            return True
    return False


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.lib_files:
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        parents = ctx.parents(relpath)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _spawn_kind(node)
            if kind is None:
                continue
            parent = parents.get(node)
            spawn = dotted_name(node.func)
            if isinstance(parent, ast.Expr):
                if kind == "task":
                    code, what = "TSA201", (
                        "the task can be garbage-collected mid-flight and "
                        "its exception is lost; retain and reap/await it "
                        "(or chain .add_done_callback)"
                    )
                else:
                    code, what = "TSA203", (
                        "the executor future's exception is silently "
                        "dropped and error paths cannot cancel it; retain "
                        "it and collect .result() (or chain "
                        ".add_done_callback)"
                    )
                findings.append(
                    Finding(
                        path=relpath,
                        line=node.lineno,
                        code=code,
                        message=f"`{spawn}(...)` result discarded: {what}",
                        key=f"discard:{spawn}",
                    )
                )
                continue
            if isinstance(parent, ast.Assign):
                targets = [
                    t for t in parent.targets if isinstance(t, ast.Name)
                ]
                if len(targets) != len(parent.targets):
                    continue  # tuple/attr targets: assume retained
                scope = _enclosing_scope(node, parents)
                if scope is None:
                    continue
                for tgt in targets:
                    if not _name_is_read(scope, tgt.id, parent):
                        if kind == "task":
                            code, noun = "TSA202", "task"
                            how = "awaited/reaped"
                        else:
                            code, noun = "TSA204", "executor future"
                            how = "collected (.result()/wrap_future)"
                        findings.append(
                            Finding(
                                path=relpath,
                                line=node.lineno,
                                code=code,
                                message=(
                                    f"{noun} assigned to `{tgt.id}` is "
                                    f"never {how} in this scope; its "
                                    "failure would be silently dropped"
                                ),
                                key=f"leak:{tgt.id}",
                            )
                        )
    return findings
