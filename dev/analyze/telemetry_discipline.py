"""Pass 4 — telemetry discipline (TSA401/TSA402).

Spans must be opened with the context manager (``with telemetry.span(...)``)
so they always close — an unclosed span corrupts the contextvar nesting for
every span recorded after it on that task. And every span/metric name must
appear in the observability catalog (``docs/observability.md``), or traces
grow unexplained tracks and dashboards silently miss data.

Codes:

- **TSA401** — ``span(...)`` called outside a ``with``/``async with`` item
  (``add_span`` is exempt: it records an already-closed interval, the
  scheduler's documented low-overhead path).
- **TSA402** — a literal span/metric name at an emission site that is not
  in the machine-readable catalog block of the observability doc. Dynamic
  (f-string) names are checked by their literal prefix; fully-dynamic names
  are skipped.

The catalog is the lines between ``analyzer: telemetry-catalog-begin`` and
``...-end`` markers, each ``span <name>`` or ``metric <name>``; ``<seg>``
segments are wildcards (``storage.<plugin>.write_bytes`` matches
``storage.fs.write_bytes``).
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, dotted_name

_CATALOG_RE = re.compile(
    r"analyzer:\s*telemetry-catalog-begin(?P<body>.*?)"
    r"analyzer:\s*telemetry-catalog-end",
    re.DOTALL,
)

# call attr/name -> (kind, index of the name argument)
_METRIC_SINKS = {
    "counter_add": ("metric", 0),
    "gauge_set": ("metric", 0),
    "gauge_max": ("metric", 0),
    "histogram_observe": ("metric", 0),
    "counter": ("metric", 0),
    "gauge": ("metric", 0),
    "histogram": ("metric", 0),
}
_SPAN_SINKS = {"span": ("span", 0), "add_span": ("span", 0)}


def parse_catalog(text: str) -> List[Tuple[str, str]]:
    """[(kind, pattern)] from the machine-readable catalog block."""
    m = _CATALOG_RE.search(text)
    if m is None:
        return []
    out = []
    for raw in m.group("body").split("\n"):
        line = raw.strip().strip("`")
        if not line or line.startswith(("#", "<!--", "```")):
            continue
        parts = line.split()
        if len(parts) == 2 and parts[0] in ("span", "metric"):
            out.append((parts[0], parts[1]))
    return out


def _glob(pattern: str) -> str:
    return re.sub(r"<[^>]*>", "*", pattern)


def _name_matches(
    kind: str, name: str, catalog: List[Tuple[str, str]]
) -> bool:
    for k, pattern in catalog:
        if k == kind and fnmatch.fnmatchcase(name, _glob(pattern)):
            return True
    return False


def _prefix_matches(
    kind: str, prefix: str, catalog: List[Tuple[str, str]]
) -> bool:
    """Lenient check for f-string names: the literal prefix must be
    compatible with some catalog entry of the same kind."""
    for k, pattern in catalog:
        if k != kind:
            continue
        g = _glob(pattern)
        literal = g.split("*", 1)[0]
        if g.startswith(prefix) or prefix.startswith(literal):
            return True
    return False


def _literal_prefix(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            break
    return "".join(parts)


def _sink_kind(call: ast.Call) -> Optional[Tuple[str, int, str]]:
    """(kind, name-arg index, sink label) when ``call`` emits telemetry."""
    name = dotted_name(call.func)
    last = None
    if name is not None:
        last = name.rsplit(".", 1)[-1]
    elif isinstance(call.func, ast.Attribute):
        last = call.func.attr  # receiver is a call/subscript result
    if last is None:
        return None
    if last in _SPAN_SINKS:
        kind, idx = _SPAN_SINKS[last]
        return kind, idx, last
    if last in _METRIC_SINKS:
        kind, idx = _METRIC_SINKS[last]
        return kind, idx, last
    return None


def _with_context_exprs(tree: ast.AST) -> Set[ast.AST]:
    out: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                out.add(item.context_expr)
    return out


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    catalog: List[Tuple[str, str]] = []
    if ctx.telemetry_catalog_path is not None:
        catalog = parse_catalog(ctx.source(ctx.telemetry_catalog_path))

    for relpath in ctx.lib_files:
        if relpath.startswith(ctx.telemetry_exempt_prefixes or ()):
            continue
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        with_exprs = _with_context_exprs(tree)
        parents = ctx.parents(relpath)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            sink = _sink_kind(node)
            if sink is None:
                continue
            kind, idx, label = sink

            # TSA401: span() must be a with-item (directly, or behind a
            # contextlib.ExitStack-style enter_context call).
            if label == "span" and node not in with_exprs:
                parent = parents.get(node)
                in_enter_context = (
                    isinstance(parent, ast.Call)
                    and (dotted_name(parent.func) or "").endswith(
                        "enter_context"
                    )
                )
                if not in_enter_context:
                    findings.append(
                        Finding(
                            path=relpath,
                            line=node.lineno,
                            code="TSA401",
                            message=(
                                "span() opened outside a `with` block; an "
                                "unclosed span corrupts nesting for the "
                                "rest of the task"
                            ),
                            key="span-no-with",
                        )
                    )

            # TSA402: the emitted name must be in the catalog.
            if ctx.telemetry_catalog_path is None or len(node.args) <= idx:
                continue
            arg = node.args[idx]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if not _name_matches(kind, arg.value, catalog):
                    findings.append(
                        Finding(
                            path=relpath,
                            line=node.lineno,
                            code="TSA402",
                            message=(
                                f"{kind} name `{arg.value}` is not in the "
                                "catalog "
                                f"({ctx.telemetry_catalog_path}); add it "
                                "there or fix the name"
                            ),
                            key=f"{kind}:{arg.value}",
                        )
                    )
            elif isinstance(arg, ast.JoinedStr):
                prefix = _literal_prefix(arg)
                if prefix and not _prefix_matches(kind, prefix, catalog):
                    findings.append(
                        Finding(
                            path=relpath,
                            line=node.lineno,
                            code="TSA402",
                            message=(
                                f"dynamic {kind} name with prefix "
                                f"`{prefix}` matches no catalog entry "
                                f"({ctx.telemetry_catalog_path})"
                            ),
                            key=f"{kind}:{prefix}*",
                        )
                    )
    return findings
