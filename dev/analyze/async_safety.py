"""Pass 1 — async-safety (TSA101-TSA103).

The whole D2H/serialize/storage-I/O overlap story (``scheduler.py``) runs on
one event loop; a single blocking call inside any ``async def`` serializes
every in-flight pipeline behind it, silently. This pass flags blocking
calls reachable *directly* in an async function body. The compliant idioms
stay quiet by construction:

- work routed through ``run_in_executor``/``asyncio.to_thread`` passes the
  callable by reference — no blocking *call node* appears in async code;
- nested sync ``def`` bodies (executor thunks like fs.py's ``work()``) are
  not part of the async body and are skipped.

Codes:

- **TSA101** — known-blocking call (``time.sleep``, builtin ``open``,
  ``os.*`` file ops, ``requests.*``, ``subprocess.*``, ``shutil.*``,
  socket/urllib) directly inside an ``async def``.
- **TSA102** — ``.result()`` on a ``concurrent.futures`` future obtained
  from ``*.submit(...)`` inside an ``async def`` (blocks the loop; await a
  wrapped future or use ``run_in_executor``). ``asyncio.Task.result()`` on
  a completed task is fine and not flagged.
- **TSA103** — event-loop re-entry (``*.run_until_complete`` /
  ``*.run_forever``) inside an ``async def``.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from .core import AnalysisContext, Finding, dotted_name

# Exact dotted names (or bare builtins) that block the calling thread.
_BLOCKING_EXACT: Set[str] = {
    "open",
    "input",
    "time.sleep",
    "os.open",
    "os.read",
    "os.write",
    "os.fsync",
    "os.sendfile",
    "os.remove",
    "os.unlink",
    "os.replace",
    "os.rename",
    "os.link",
    "os.symlink",
    "os.makedirs",
    "os.mkdir",
    "os.rmdir",
    "os.listdir",
    "os.scandir",
    "os.stat",
    "os.lstat",
    "os.truncate",
    "os.system",
    "io.open",
    "socket.create_connection",
    "socket.getaddrinfo",
    "socket.gethostbyname",
    "urllib.request.urlopen",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "shutil.copy",
    "shutil.copy2",
    "shutil.copyfile",
    "shutil.copytree",
    "shutil.move",
    "shutil.rmtree",
}

# Any call into these modules blocks (sync HTTP clients).
_BLOCKING_PREFIXES = ("requests.",)

_LOOP_REENTRY_ATTRS = {"run_until_complete", "run_forever"}


def _direct_body(fn: ast.AsyncFunctionDef) -> Iterator[ast.AST]:
    """Every node of ``fn``'s body that executes on the event loop: stop at
    nested function/lambda boundaries (sync nested defs are executor thunks;
    nested async defs are visited as their own async functions)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _blocking_reason(name: Optional[str]) -> Optional[str]:
    if name is None:
        return None
    if name in _BLOCKING_EXACT:
        return name
    for prefix in _BLOCKING_PREFIXES:
        if name.startswith(prefix):
            return name
    return None


def _check_async_fn(
    relpath: str, fn: ast.AsyncFunctionDef, findings: List[Finding]
) -> None:
    # Names bound from ``<pool>.submit(...)`` in THIS async body: calling
    # .result() on them synchronously waits out the worker thread.
    executor_futures: Set[str] = set()
    body = list(_direct_body(fn))
    for node in body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted_name(node.value.func)
            if callee is not None and callee.endswith(".submit"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        executor_futures.add(tgt.id)

    for node in body:
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        reason = _blocking_reason(name)
        if reason is not None:
            findings.append(
                Finding(
                    path=relpath,
                    line=node.lineno,
                    code="TSA101",
                    message=(
                        f"blocking call `{reason}` inside `async def "
                        f"{fn.name}` stalls the event loop; route it "
                        "through run_in_executor/asyncio.to_thread"
                    ),
                    key=f"{fn.name}:{reason}",
                )
            )
            continue
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv = node.func.value
            if attr == "result" and not node.args:
                recv_is_submit_chain = (
                    isinstance(recv, ast.Call)
                    and (dotted_name(recv.func) or "").endswith(".submit")
                )
                recv_is_tracked = (
                    isinstance(recv, ast.Name) and recv.id in executor_futures
                )
                if recv_is_submit_chain or recv_is_tracked:
                    findings.append(
                        Finding(
                            path=relpath,
                            line=node.lineno,
                            code="TSA102",
                            message=(
                                "blocking Future.result() on an executor "
                                f"future inside `async def {fn.name}`; "
                                "await asyncio.wrap_future(...) or use "
                                "run_in_executor"
                            ),
                            key=f"{fn.name}:result",
                        )
                    )
            elif attr in _LOOP_REENTRY_ATTRS:
                findings.append(
                    Finding(
                        path=relpath,
                        line=node.lineno,
                        code="TSA103",
                        message=(
                            f"event-loop re-entry `{attr}` inside `async "
                            f"def {fn.name}`; await the coroutine instead"
                        ),
                        key=f"{fn.name}:{attr}",
                    )
                )


def run(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.lib_files:
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.AsyncFunctionDef):
                _check_async_fn(relpath, node, findings)
    return findings
