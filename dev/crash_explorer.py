"""Crash-state explorer: prove every durable-effect prefix is restorable.

The effect journal (``torchsnapshot_tpu/effect_journal.py``, enabled by the
``TORCHSNAPSHOT_TPU_DEBUG_EFFECTS`` knob) records the total order in which
mutations reached storage during a run. A single-process crash at any
instant leaves behind exactly a prefix of that order — plus, for a crash
mid-write, a partial tail of the in-flight payload. This module replays
each such prefix into a fresh on-disk store and asserts the lifecycle
layer's crash-consistency contract on the materialized state:

A. **Restorable**: every catalog-visible snapshot (``.snapshot_metadata``
   present) passes ``Snapshot.verify()`` — all manifest-referenced objects
   exist and match their recorded CRCs bit-exactly. A ``restore_check``
   callback lets suites additionally drive a real restore.
B. **No publish-before-payload**: a catalog record never points at a
   snapshot whose ``.snapshot_metadata`` is absent, unless an earlier
   effect in the same prefix deleted that metadata (a mid-GC *zombie*,
   which the next GC run finishes by contract).
C. **GC convergence**: on a copy of the crash state, ``Snapshot.gc``
   (full sweep) followed by a second run removes nothing further, and
   every snapshot that verified clean before GC still verifies clean
   after — GC never touches committed bytes.

Failures carry the exact effect sequence number and originating call site
of the last applied effect: "a crash immediately after effect #N (site S)
leaves an unrestorable state".

Replay model (matches the fs backend's crash window, and is conservative
for atomic backends): ``write``/``link`` materialize the final object
whole; ``stream_open`` creates a ``*.tmp.*`` temp file; ``append`` grows
it; ``commit`` renames it over the final path; ``abort``/``delete``
remove. Interior samples (seeded, deterministic) cut an in-flight payload
at a byte boundary and land the partial bytes where a real crash would:
appended to the stream temp file, or as ``*.tmp.*`` debris for an atomic
write — never at the final path.

The journal records origins (plugin roots) from any backend; replay always
targets the local filesystem, so a journal captured against ``memory://``
is explored with the same code. During verification the explorer
neutralizes the fault-injection / effect-journal / read-cache knobs: the
checks themselves construct plugins via ``url_to_storage_plugin`` and must
observe the replayed bytes, not re-journal or re-fault them.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import shutil
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

# Knobs that would make the *checks* (verify/gc, which build their own
# storage plugins) observe something other than the replayed bytes.
_NEUTRALIZED_KNOBS = (
    "TORCHSNAPSHOT_TPU_FAULTS",
    "TORCHSNAPSHOT_TPU_DEBUG_EFFECTS",
    "TORCHSNAPSHOT_TPU_READ_CACHE_DIR",
)

_METADATA_FNAME = ".snapshot_metadata"
_CATALOG_DIR = ".catalog"
_RECORD_DIR = ".catalog/records"


@contextlib.contextmanager
def _pristine_env():
    saved = {}
    for name in _NEUTRALIZED_KNOBS:
        if name in os.environ:
            saved[name] = os.environ.pop(name)
    try:
        yield
    finally:
        os.environ.update(saved)


@dataclass(frozen=True)
class Violation:
    """One crash state that breaks the contract, attributed to the last
    applied effect (crash 'immediately after effect #seq')."""

    prefix_len: int
    seq: int
    op: str
    path: str
    site: str
    problem: str
    interior: Optional[str] = None  # "k/n bytes" for mid-payload samples

    def render(self) -> str:
        where = f"effect #{self.seq} ({self.op} {self.path}) at {self.site}"
        cut = f" [interior: {self.interior}]" if self.interior else ""
        return (
            f"crash after {where}{cut} "
            f"(prefix of {self.prefix_len} effect(s)): {self.problem}"
        )


@dataclass
class ExplorationReport:
    prefixes: int = 0
    interior_samples: int = 0
    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        head = (
            f"crash explorer: {self.prefixes} prefix(es), "
            f"{self.interior_samples} interior sample(s), "
            f"{len(self.violations)} violation(s)"
        )
        return "\n".join([head] + [f"  {v.render()}" for v in self.violations])


class CrashStateViolation(AssertionError):
    """Raised (by default) when any explored prefix breaks the contract."""

    def __init__(self, report: ExplorationReport) -> None:
        self.report = report
        super().__init__(report.render())


# ---------------------------------------------------------------------------
# Replay
# ---------------------------------------------------------------------------


def _common_base(origins: Sequence[str]) -> str:
    uniq = sorted(set(origins))
    if not uniq:
        return ""
    if len(uniq) == 1:
        return uniq[0]
    return os.path.commonpath(uniq)


class _ReplayState:
    """One incrementally-built crash state on disk.

    ``root`` mirrors the journal's common origin base (for the usual
    single-bucket run, the bucket itself)."""

    def __init__(self, root: str, base: str) -> None:
        self.root = root
        self.base = base
        os.makedirs(root, exist_ok=True)
        # stream_id -> (final abs path, temp abs path)
        self.streams: Dict[int, Tuple[str, str]] = {}
        # Mapped abs targets of every applied delete, for the zombie
        # exemption in invariant B.
        self.deleted: Set[str] = set()

    def map_path(self, origin: str, path: str) -> str:
        logical = os.path.normpath(os.path.join(origin, path))
        rel = os.path.relpath(logical, self.base)
        return os.path.normpath(os.path.join(self.root, rel))

    def _materialize(self, abs_path: str, payload: Optional[bytes]) -> None:
        os.makedirs(os.path.dirname(abs_path), exist_ok=True)
        with open(abs_path, "wb") as f:
            f.write(payload or b"")

    def apply(self, effect) -> None:
        abs_path = self.map_path(effect.origin, effect.path)
        if effect.op in ("write", "link"):
            self._materialize(abs_path, effect.payload)
        elif effect.op == "stream_open":
            tmp = f"{abs_path}.tmp.replay{effect.stream_id}"
            self._materialize(tmp, b"")  # fs opens the temp file eagerly
            self.streams[effect.stream_id] = (abs_path, tmp)
        elif effect.op == "append":
            entry = self.streams.get(effect.stream_id)
            if entry is not None:
                with open(entry[1], "ab") as f:
                    f.write(effect.payload or b"")
        elif effect.op == "commit":
            entry = self.streams.pop(effect.stream_id, None)
            if entry is not None and os.path.exists(entry[1]):
                os.replace(entry[1], entry[0])
        elif effect.op == "abort":
            entry = self.streams.pop(effect.stream_id, None)
            if entry is not None and os.path.exists(entry[1]):
                os.remove(entry[1])
        elif effect.op == "delete":
            self.deleted.add(abs_path)
            if os.path.isfile(abs_path):
                os.remove(abs_path)

    def apply_partial(self, effect, cut: int) -> None:
        """Land the first ``cut`` bytes of an in-flight payload where a
        real crash would leave them (see module docstring)."""
        partial = (effect.payload or b"")[:cut]
        abs_path = self.map_path(effect.origin, effect.path)
        if effect.op == "append":
            entry = self.streams.get(effect.stream_id)
            if entry is not None:
                with open(entry[1], "ab") as f:
                    f.write(partial)
        elif effect.op in ("write", "link"):
            self._materialize(f"{abs_path}.tmp.partial", partial)


# ---------------------------------------------------------------------------
# Invariant checks over one materialized crash state
# ---------------------------------------------------------------------------


def _committed_roots(state_root: str) -> List[str]:
    roots = []
    for dirpath, dirnames, filenames in os.walk(state_root):
        if _CATALOG_DIR in dirnames:
            dirnames.remove(_CATALOG_DIR)
        if _METADATA_FNAME in filenames:
            roots.append(dirpath)
    return sorted(roots)


def _catalog_record_targets(state_root: str) -> List[Tuple[str, str]]:
    """(record file, snapshot root abs path) for every parseable catalog
    record in the state (unparseable files are GC's problem, not ours)."""
    out = []
    for dirpath, _, filenames in os.walk(state_root):
        rel = os.path.relpath(dirpath, state_root).replace(os.sep, "/")
        if _RECORD_DIR not in f"{rel}/":
            continue
        bucket = dirpath
        while os.path.basename(bucket) != _CATALOG_DIR:
            bucket = os.path.dirname(bucket)
        bucket = os.path.dirname(bucket)
        for fname in filenames:
            record_file = os.path.join(dirpath, fname)
            try:
                with open(record_file, encoding="utf-8") as f:
                    name = str(json.load(f)["name"])
            except Exception:  # noqa: BLE001 - unclassifiable record
                continue
            out.append((record_file, os.path.join(bucket, name)))
    return sorted(out)


def _gc_targets(state_root: str) -> List[str]:
    """Directories ``Snapshot.gc`` should sweep: each bucket (dir holding a
    ``.catalog/`` or a committed child), or a bare committed root."""
    targets: Set[str] = set()
    for dirpath, dirnames, filenames in os.walk(state_root):
        if _CATALOG_DIR in dirnames:
            targets.add(dirpath)
        if _METADATA_FNAME in filenames:
            targets.add(
                state_root if dirpath == state_root else os.path.dirname(dirpath)
            )
    # Nested targets would double-sweep; keep outermost only.
    out: List[str] = []
    for t in sorted(targets):
        if not any(t.startswith(kept + os.sep) for kept in out):
            out.append(t)
    return out


def _check_state(
    state: _ReplayState,
    restore_check: Optional[Callable[[str], None]],
) -> List[str]:
    """Invariants A and B on the live state (read-only). Returns problem
    strings; the caller attributes them to the crash point."""
    from torchsnapshot_tpu import Snapshot

    problems: List[str] = []
    for root in _committed_roots(state.root):
        try:
            bad = Snapshot(path=root).verify()
        except Exception as e:  # noqa: BLE001 - any failure = unrestorable
            problems.append(f"committed snapshot {root} failed verify: {e}")
            continue
        if bad:
            worst = "; ".join(f"{p}: {why}" for p, why in sorted(bad.items()))
            problems.append(
                f"committed snapshot {root} is not bit-exact: {worst}"
            )
            continue
        if restore_check is not None:
            try:
                restore_check(root)
            except Exception as e:  # noqa: BLE001 - restore is the contract
                problems.append(
                    f"committed snapshot {root} failed restore check: {e}"
                )
    for record_file, snap_root in _catalog_record_targets(state.root):
        meta = os.path.join(snap_root, _METADATA_FNAME)
        if os.path.exists(meta):
            continue
        if meta in state.deleted:
            continue  # mid-GC zombie: record outlives metadata by contract
        problems.append(
            f"catalog record {os.path.relpath(record_file, state.root)} "
            f"published before {os.path.relpath(meta, state.root)} exists "
            "(publish-before-payload)"
        )
    return problems


def _check_gc_convergence(state_root: str, scratch: str) -> List[str]:
    """Invariant C on a copy: full-sweep GC converges in one run and never
    touches committed bytes."""
    from torchsnapshot_tpu import Snapshot

    problems: List[str] = []
    if os.path.exists(scratch):
        shutil.rmtree(scratch)
    shutil.copytree(state_root, scratch)
    clean_before = []
    for root in _committed_roots(scratch):
        try:
            if not Snapshot(path=root).verify():
                clean_before.append(root)
        except Exception:  # noqa: BLE001 - A already reported it
            pass
    for target in _gc_targets(scratch):
        try:
            Snapshot.gc(target, dry_run=False)
            second = Snapshot.gc(target, dry_run=False)
        except Exception as e:  # noqa: BLE001 - gc must not fail
            problems.append(f"gc failed on crash state under {target}: {e}")
            continue
        leftovers = second.get("remove", [])
        if leftovers:
            problems.append(
                f"gc did not converge under {target}: second run still "
                f"removes {sorted(leftovers)[:5]}"
            )
    for root in clean_before:
        try:
            bad = Snapshot(path=root).verify()
        except Exception as e:  # noqa: BLE001 - gc ate the snapshot
            problems.append(
                f"gc broke committed snapshot {root}: verify now fails: {e}"
            )
            continue
        if bad:
            worst = "; ".join(f"{p}: {why}" for p, why in sorted(bad.items()))
            problems.append(f"gc touched committed bytes under {root}: {worst}")
    shutil.rmtree(scratch, ignore_errors=True)
    return problems


# ---------------------------------------------------------------------------
# Exploration driver
# ---------------------------------------------------------------------------


def _interior_plan(effects, seed: int, interior_samples: int):
    """Deterministic (index, cut) samples: which in-flight payloads to cut,
    and where. Same seed + same journal => same plan."""
    rng = random.Random(seed)
    candidates = [
        i
        for i, e in enumerate(effects)
        if e.op in ("write", "append", "link") and e.nbytes > 1
    ]
    picked = sorted(rng.sample(candidates, min(interior_samples, len(candidates))))
    return [(i, rng.randrange(1, effects[i].nbytes)) for i in picked]


def explore(
    effects,
    workdir: str,
    *,
    seed: int = 0,
    interior_samples: int = 2,
    check_gc: bool = True,
    restore_check: Optional[Callable[[str], None]] = None,
    raise_on_violation: bool = True,
) -> ExplorationReport:
    """Replay every prefix of ``effects`` (plus seeded interior samples)
    under ``workdir`` and assert invariants A/B/C on each crash state.

    ``restore_check(root_abs_path)`` optionally drives a real restore per
    committed snapshot. Raises :class:`CrashStateViolation` naming the
    exact effect seq and call site unless ``raise_on_violation=False``."""
    effects = list(effects)
    report = ExplorationReport()
    base = _common_base([e.origin for e in effects])
    state_dir = os.path.join(workdir, "state")
    scratch = os.path.join(workdir, "scratch")
    if os.path.exists(state_dir):
        shutil.rmtree(state_dir)
    state = _ReplayState(state_dir, base)
    plan = dict(_interior_plan(effects, seed, interior_samples))

    def _record(problems, prefix_len, effect, interior=None):
        for problem in problems:
            report.violations.append(
                Violation(
                    prefix_len=prefix_len,
                    seq=effect.seq,
                    op=effect.op,
                    path=effect.path,
                    site=effect.site,
                    problem=problem,
                    interior=interior,
                )
            )

    with _pristine_env():
        for i, effect in enumerate(effects):
            cut = plan.get(i)
            if cut is not None:
                # Crash MID effect i: state holds effects[:i] plus a
                # partial tail of effect i's payload. Checked on a copy so
                # the live state stays an exact op-boundary prefix.
                partial_dir = os.path.join(workdir, "partial")
                if os.path.exists(partial_dir):
                    shutil.rmtree(partial_dir)
                shutil.copytree(state_dir, partial_dir)
                pstate = _ReplayState(partial_dir, base)
                pstate.deleted = set(state.deleted)

                def _reroot(p: str) -> str:
                    return os.path.join(
                        partial_dir, os.path.relpath(p, state_dir)
                    )

                pstate.streams = {
                    sid: (_reroot(final), _reroot(tmp))
                    for sid, (final, tmp) in state.streams.items()
                }
                pstate.apply_partial(effect, cut)
                interior = f"{cut}/{effect.nbytes} bytes"
                report.interior_samples += 1
                _record(
                    _check_state(pstate, restore_check), i, effect, interior
                )
                if check_gc:
                    _record(
                        _check_gc_convergence(partial_dir, scratch),
                        i,
                        effect,
                        interior,
                    )
                shutil.rmtree(partial_dir, ignore_errors=True)

            state.apply(effect)
            report.prefixes += 1
            _record(_check_state(state, restore_check), i + 1, effect)
            if check_gc:
                _record(_check_gc_convergence(state_dir, scratch), i + 1, effect)

    if report.violations and raise_on_violation:
        raise CrashStateViolation(report)
    return report


def explore_journal(workdir: str, **kwargs) -> ExplorationReport:
    """Explore the process-wide effect journal (the usual test entry point:
    run a scenario under ``TORCHSNAPSHOT_TPU_DEBUG_EFFECTS=1``, then call
    this). Raises if the journal is disabled or empty — a silent no-op
    would read as coverage."""
    from torchsnapshot_tpu import effect_journal

    journal = effect_journal.get_journal()
    if journal is None:
        raise RuntimeError(
            "effect journal is disabled; set TORCHSNAPSHOT_TPU_DEBUG_EFFECTS=1 "
            "(or knobs.override_debug_effects) before the scenario runs"
        )
    effects = journal.effects()
    if not effects:
        raise RuntimeError("effect journal is empty; nothing was explored")
    return explore(effects, workdir, **kwargs)
