"""Incremental-snapshot benchmark: the LoRA/partial-finetune checkpoint.

No reference analogue (the reference rewrites every byte each checkpoint).
State shape: a large frozen backbone + small trainable adapters. Each
checkpoint interval, only the adapters changed; ``take(base=prev)``
hard-links the frozen objects and writes just the changed bytes.

  python benchmarks/incremental/main.py --frozen-gb 1 --adapter-mb 16
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def main() -> None:
    # Pin dedup digests ON: the auto default disables them on single-vCPU
    # hosts, and a base taken without sha256 identities silently degrades
    # every incremental take to a full rewrite — this benchmark would then
    # "pass" while measuring nothing (ADVICE round 5).
    os.environ["TORCHSNAPSHOT_TPU_DEDUP_DIGESTS"] = "1"
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--frozen-gb", type=float, default=1.0)
    parser.add_argument("--adapter-mb", type=float, default=16.0)
    args = parser.parse_args()

    from torchsnapshot_tpu import Snapshot, StateDict

    rng = np.random.default_rng(0)
    n_frozen = max(1, int(args.frozen_gb * 1e9 / (64 * 1024 * 1024)))
    frozen = {
        f"backbone{i}": rng.standard_normal(16 * 1024 * 1024).astype(np.float32)
        for i in range(n_frozen)
    }
    n_adapt = max(1, int(args.adapter_mb * 1e6 / (4 * 1024 * 1024)))
    adapters = {
        f"lora{i}": rng.standard_normal(1024 * 1024).astype(np.float32)
        for i in range(n_adapt)
    }
    total_gb = sum(a.nbytes for a in {**frozen, **adapters}.values()) / 1e9
    root = tempfile.mkdtemp(prefix="tss_inc_")

    def app():
        return {"m": StateDict(**frozen, **adapters)}

    t0 = time.perf_counter()
    Snapshot.take(os.path.join(root, "step0"), app())
    full_s = time.perf_counter() - t0
    print(f"full take: {total_gb:.2f} GB in {full_s:.2f}s")

    # "Train": only the adapters change.
    for k in adapters:
        adapters[k] = adapters[k] + 1.0

    t0 = time.perf_counter()
    Snapshot.take(
        os.path.join(root, "step1"), app(), base=os.path.join(root, "step0")
    )
    inc_s = time.perf_counter() - t0
    changed_gb = sum(a.nbytes for a in adapters.values()) / 1e9
    print(
        f"incremental take: {total_gb:.2f} GB state, {changed_gb:.3f} GB "
        f"changed, {inc_s:.2f}s ({full_s / inc_s:.1f}x faster than full)"
    )

    # Hard-linking must actually have happened: a silent fallback to full
    # rewrites (digests missing, cross-device link failure) would otherwise
    # report a bogus "speedup". Same inode == same bytes on disk.
    loc = Snapshot(os.path.join(root, "step1")).get_manifest()[
        "0/m/backbone0"
    ].location
    assert os.path.samefile(
        os.path.join(root, "step0", loc), os.path.join(root, "step1", loc)
    ), "backbone object was rewritten, not hard-linked — dedup silently degraded"

    out = StateDict()
    Snapshot(os.path.join(root, "step1")).restore({"m": out})
    ok = np.array_equal(out["lora0"], adapters["lora0"]) and np.array_equal(
        out["backbone0"], frozen["backbone0"]
    )
    print(f"restore bit-exact: {ok}; verify: {Snapshot(os.path.join(root, 'step1')).verify() == {}}")

    import shutil

    shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
