#!/usr/bin/env bash
# Multi-host TPU-pod launch recipe for every benchmark in this directory —
# the TPU analogue of the reference's per-benchmark `run.slurm`
# (e.g. /root/reference/benchmarks/fsdp/run.slurm, which wraps
# torch.distributed.run under SLURM). On Cloud TPU there is no SLURM: the
# pod's hosts are addressed with `gcloud ... tpu-vm ssh --worker=all`, and
# jax.distributed discovers peers through the TPU metadata service, so the
# same command runs unmodified on every worker.
#
# Usage (from your workstation):
#
#   ./run_tpu_vm.sh <tpu-name> <zone> <benchmark> [args...]
#
#   ./run_tpu_vm.sh v5e-pod us-west4-a stall --steps 5
#   ./run_tpu_vm.sh v5e-pod us-west4-a fsdp --ckpt-path gs://my-bucket/bench
#
# What it does on every worker:
#   1. syncs this repository to the worker (rsync over ssh);
#   2. runs the benchmark with `jax.distributed.initialize()` auto-config —
#      on Cloud TPU, coordinator address/rank/world come from the metadata
#      service, no flags needed;
#   3. the checkpoint target should be a GCS bucket (gs://...) reachable
#      from the pod's service account; per-host local paths also work for
#      single-host measurements but do NOT produce a restorable pod
#      snapshot unless the filesystem is shared.
#
# Knobs worth setting at pod scale (exported below, override via env):
#   TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT_S — commit barriers legitimately wait
#     for the SLOWEST host's data drain; size it at (bytes_per_host /
#     worst-case GB/s to the bucket) + headroom. This script exports 600 s
#     (covers ~250 GB/host at 0.5 GB/s); raise it for bigger states.
#   TORCHSNAPSHOT_TPU_GCS_CHUNK_BYTES — resumable-upload chunk (default
#     100 MB): smaller chunks re-send less on a fault, larger chunks make
#     fewer round-trips.
#
# Preemption behavior (what to expect): if any host dies mid-take, the
# commit barrier propagates the failure and NO `.snapshot_metadata` is
# written — the previous snapshot stays the newest committed one, and the
# restarted job resumes from it (tests/test_async_take.py drills this with
# SIGKILL). Partially-written objects of the aborted take are overwritten
# by the next take to the same path or cleaned by a bucket lifecycle rule.

set -euo pipefail

TPU_NAME=${1:?tpu name}
ZONE=${2:?zone}
BENCH=${3:?benchmark dir under benchmarks/}
shift 3

REPO_DIR=$(cd "$(dirname "$0")/.." && pwd)
REMOTE_DIR=/tmp/torchsnapshot_tpu_bench

echo ">>> syncing repo to all workers"
gcloud compute tpus tpu-vm scp --recurse --worker=all --zone="$ZONE" \
  "$REPO_DIR" "$TPU_NAME:$REMOTE_DIR"

echo ">>> running benchmarks/$BENCH on all workers"
gcloud compute tpus tpu-vm ssh "$TPU_NAME" --worker=all --zone="$ZONE" \
  --command="
    export BENCH_DISTRIBUTED=1
    export TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT_S=\${TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT_S:-600}
    cd $REMOTE_DIR && python3 benchmarks/$BENCH/main.py $*"
