"""Restore-overlap A/B on real hardware (VERDICT round 4, item 5).

The overlapped restore (``TORCHSNAPSHOT_TPU_RESTORE_OVERLAP``) finalizes
each entry's host→device transfer inline as its last storage read consumes,
instead of phase-splitting all H2D after the read pipeline. Until round 5
the overlap win was demonstrated only on a synthetic latency-bound storage
fake (``tests/test_restore_overlap.py``); this harness measures both modes
on real hardware, wall + peak RSS, interleaved with alternating order. Its
round-5 run on the 1-vCPU host + real TPU (overlap 3.60 s vs phase-split
5.57 s median, peak RSS 0.94 vs 1.32 GB; ``results_round5_tpu.txt``) is
what flipped the auto gate to platform-aware: accelerator-backend H2D
dispatch is a PJRT hand-off, so overlap wins even with no spare core —
only the CPU backend on one core keeps the phase split.

  python benchmarks/restore_overlap/main.py --gb 0.5 --reps 3

Reports one row per mode: median wall, spread, median peak RSS delta.
"""

import argparse
import os
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=0.5)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument(
        "--cpu", action="store_true", help="force the (multi-device) CPU platform"
    )
    args = parser.parse_args()

    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.utils import knobs
    from torchsnapshot_tpu.utils.rss_profiler import measure_rss_deltas

    d = jax.devices()[0]
    print(f"device: {d.device_kind} ({d.platform})", file=sys.stderr)

    n_arrays = max(2, round(args.gb * 1e9 / (32 * 1024 * 1024)))
    ks = jax.random.split(jax.random.PRNGKey(0), n_arrays)
    state = {
        f"a{i}": jax.random.normal(ks[i], (2048, 8192), jnp.bfloat16)
        for i in range(n_arrays)
    }
    jax.block_until_ready(state)
    gb = sum(x.nbytes for x in state.values()) / 1e9
    print(f"state: {gb:.2f} GB in {n_arrays} arrays", file=sys.stderr)

    root = tempfile.mkdtemp(prefix="tss_overlap_")
    path = os.path.join(root, "ckpt")
    Snapshot.take(path, {"m": StateDict(**state)})

    def run_restore(overlap: bool):
        tgt = StateDict(
            **{k: jnp.zeros_like(v) for k, v in state.items()}
        )
        jax.block_until_ready(dict(tgt))
        deltas = [0]
        with knobs.override_restore_overlap(overlap):
            t0 = time.perf_counter()
            with measure_rss_deltas(rss_deltas=deltas):
                Snapshot(path).restore({"m": tgt})
            wall = time.perf_counter() - t0
        a0 = tgt["a0"]
        assert np.array_equal(
            np.asarray(a0).view(np.uint8), np.asarray(state["a0"]).view(np.uint8)
        )
        return wall, max(deltas)

    # Warm both paths once (jit/plan caches, page cache for the reads).
    run_restore(False)
    run_restore(True)

    results = {False: [], True: []}
    for rep in range(args.reps):
        order = [False, True] if rep % 2 == 0 else [True, False]
        for overlap in order:
            wall, rss = run_restore(overlap)
            results[overlap].append((wall, rss))
            print(
                f"rep {rep} overlap={'on' if overlap else 'off'}: "
                f"{wall:.2f}s, peak RSS delta {rss/1e9:.2f} GB",
                file=sys.stderr,
            )

    print(f"--- restore of {gb:.2f} GB, {args.reps} interleaved reps/mode")
    print(f"{'mode':>14} {'median_s':>9} {'spread_s':>15} {'peak_rss_gb':>12}")
    for overlap in (False, True):
        walls = [w for w, _ in results[overlap]]
        rsss = [r for _, r in results[overlap]]
        print(
            f"{('overlap' if overlap else 'phase-split'):>14} "
            f"{statistics.median(walls):>9.2f} "
            f"{min(walls):>7.2f}-{max(walls):<7.2f} "
            f"{statistics.median(rsss)/1e9:>12.2f}"
        )


if __name__ == "__main__":
    main()
