"""Budgeted single-array load (reference ``benchmarks/load_tensor/main.py``:
a 10 GB tensor read under a 100 MB RSS budget).

Proves ``read_object(memory_budget_bytes=...)`` caps host memory: the array
is fetched as budget-sized byte ranges written straight into the target.

  python benchmarks/load_tensor/main.py --gb 2 --budget-mb 100
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=1.0)
    parser.add_argument("--budget-mb", type=int, default=100)
    args = parser.parse_args()

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.utils.rss_profiler import measure_rss_deltas

    n = int(args.gb * 1e9 / 4)
    arr = np.arange(n, dtype=np.float32)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "ckpt")
        Snapshot.take(path, {"s": StateDict(big=arr)})

        target = np.zeros_like(arr)
        budget = args.budget_mb * 1024 * 1024
        deltas = []
        t0 = time.perf_counter()
        with measure_rss_deltas(rss_deltas=deltas):
            Snapshot(path).read_object(
                "0/s/big", obj_out=target, memory_budget_bytes=budget
            )
        elapsed = time.perf_counter() - t0
        peak_mb = max(deltas) / 1e6
        print(
            f"read {args.gb:.1f} GB with {args.budget_mb} MB budget: "
            f"{elapsed:.2f}s, peak RSS delta {peak_mb:.0f} MB"
        )
        assert np.array_equal(target, arr)
        print("bit-exact: True")


if __name__ == "__main__":
    main()
