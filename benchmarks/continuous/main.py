"""Continuous-checkpointing benchmark: sustained delta chains at bounded
bucket growth, plus the chain-aware warm restore.

The write-side mirror of ``benchmarks/serving``: one job snapshots every
"step" into one bucket via catalog-managed delta chains
(``Snapshot.take(job=...)`` auto-selects each take's ``base=`` and rebases
to a full snapshot at ``max_chain_len``), a keep-last-K retention policy
runs every ``RETAIN_EVERY`` steps, and the harness asserts the two
production claims end to end:

1. **Bounded growth** — with retention on, bucket bytes PLATEAU as snapshot
   count grows without bound (keep-last-K ⇒ steady-state size ≈ the live
   window, not the history). Bytes are measured inode-deduped (fs hard
   links are the dedup substrate: N chain members sharing a frozen object
   cost its bytes once).

2. **Chain-aware warm restore** — a replica that restored step T-1 with the
   content-addressed read cache on restores step T reading ≈ only that
   delta's NEW bytes from origin: chain-shared objects hit the digest-keyed
   cache (one entry per content across the chain), so origin traffic is the
   adapter delta, not the full state.

Also reported: sustained checkpoints/minute, per-step wall times, chain
shape (rebase cadence), and the bucket-bytes-vs-snapshot-count series.

  python benchmarks/continuous/main.py            # acceptance scale (50+)
  CONTINUOUS_BENCH_STEPS=8 ... main.py            # smoke scale (tier-1)

Env knobs: CONTINUOUS_BENCH_STEPS (default 60), CONTINUOUS_BENCH_KEEP_LAST
(5), CONTINUOUS_BENCH_RETAIN_EVERY (5), CONTINUOUS_BENCH_MAX_CHAIN (8),
CONTINUOUS_BENCH_FROZEN_MB (32), CONTINUOUS_BENCH_ADAPTER_MB (2).
CONTINUOUS_BENCH_EXPECT_ANOMALY selects the health-detector contract:
unset/"" asserts zero anomalies on the clean run (no false positives);
"stall" asserts a stall_spike IS detected. CONTINUOUS_BENCH_FAULT_STEP
(default: 3/4 through the run when EXPECT_ANOMALY=stall) picks the step
whose take runs under CONTINUOUS_BENCH_FAULT_SPEC (a faults.py spec,
default a 1.5s write stall) — fault-rule state lives per plugin instance
(one per take), so an env-level spec would stall EVERY step and never
spike against its own trailing median; the harness scopes the knob to
the one step instead.
The last JSON line on stdout is the machine-readable result.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def bucket_bytes(root: str) -> int:
    """Bytes the bucket actually occupies, hard-link (inode) deduped —
    the number retention must bound."""
    seen = set()
    total = 0
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in filenames:
            try:
                st = os.stat(os.path.join(dirpath, fname))
            except OSError:
                continue
            key = (st.st_dev, st.st_ino)
            if key not in seen:
                seen.add(key)
                total += st.st_size
    return total


def main() -> None:
    # Dedup digests must be pinned on: the auto default disables them on
    # single-vCPU hosts and the whole chain story silently degrades to
    # full rewrites (same rationale as benchmarks/incremental).
    os.environ["TORCHSNAPSHOT_TPU_DEDUP_DIGESTS"] = "1"
    maybe_init_distributed()

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu import catalog
    from torchsnapshot_tpu import snapshot as snapshot_mod
    from torchsnapshot_tpu.telemetry import health, steprecord
    from torchsnapshot_tpu.utils import knobs

    steps = int(os.environ.get("CONTINUOUS_BENCH_STEPS", "60"))
    keep_last = int(os.environ.get("CONTINUOUS_BENCH_KEEP_LAST", "5"))
    retain_every = int(os.environ.get("CONTINUOUS_BENCH_RETAIN_EVERY", "5"))
    max_chain = int(os.environ.get("CONTINUOUS_BENCH_MAX_CHAIN", "8"))
    frozen_mb = float(os.environ.get("CONTINUOUS_BENCH_FROZEN_MB", "32"))
    adapter_mb = float(os.environ.get("CONTINUOUS_BENCH_ADAPTER_MB", "2"))
    expect = os.environ.get("CONTINUOUS_BENCH_EXPECT_ANOMALY", "")
    fault_step = int(
        os.environ.get(
            "CONTINUOUS_BENCH_FAULT_STEP",
            str(steps * 3 // 4) if expect == "stall" else "-1",
        )
    )
    fault_spec = os.environ.get(
        "CONTINUOUS_BENCH_FAULT_SPEC", "op=write,kind=stall,secs=1.5,at=0"
    )

    rng = np.random.default_rng(0)
    n_frozen = max(1, int(frozen_mb * 1e6 / (4 * 1024 * 1024)))
    frozen = {
        f"backbone{i}": rng.standard_normal(1024 * 1024).astype(np.float32)
        for i in range(n_frozen)
    }
    n_adapt = max(1, int(adapter_mb * 1e6 / (256 * 1024)))
    adapters = {
        f"lora{i}": rng.standard_normal(64 * 1024).astype(np.float32)
        for i in range(n_adapt)
    }
    frozen_bytes = sum(a.nbytes for a in frozen.values())
    adapter_bytes = sum(a.nbytes for a in adapters.values())

    root = tempfile.mkdtemp(prefix="tss_continuous_")
    bucket = os.path.join(root, "bucket")
    os.makedirs(bucket)
    cache_dir = os.path.join(root, "cache")
    policy = catalog.RetentionPolicy.parse(f"last={keep_last}")

    take_walls = []
    size_series = []  # (snapshot_count_taken, bucket_bytes)
    # Job-lifetime step-telemetry series. Retention GC deletes a condemned
    # snapshot's step record along with it, so the catalog only ever holds
    # the live window — the bench accumulates the full series by syncing
    # BEFORE each retention pass (and once after the loop).
    step_series = []
    seen_steps = set()

    def sync_step_series():
        try:
            with catalog.Catalog(bucket) as cat:
                for rec in cat.load_step_telemetry(job="continuous-bench"):
                    if rec.get("step") not in seen_steps:
                        seen_steps.add(rec.get("step"))
                        step_series.append(rec)
        except Exception:  # noqa: BLE001 - telemetry is fail-open
            pass

    t_begin = time.perf_counter()
    try:
        for step in range(steps):
            # "Train": only the adapters change between checkpoints.
            for k in adapters:
                adapters[k] = adapters[k] + 1.0
            app = {"m": StateDict(**frozen, **adapters)}
            saved_faults = os.environ.get("TORCHSNAPSHOT_TPU_FAULTS")
            if step == fault_step:
                os.environ["TORCHSNAPSHOT_TPU_FAULTS"] = fault_spec
            t0 = time.perf_counter()
            try:
                Snapshot.take(
                    os.path.join(bucket, f"step_{step:05d}"),
                    app,
                    job="continuous-bench",
                    step=step,
                    max_chain_len=max_chain,
                )
            finally:
                if step == fault_step:
                    if saved_faults is None:
                        os.environ.pop("TORCHSNAPSHOT_TPU_FAULTS", None)
                    else:
                        os.environ["TORCHSNAPSHOT_TPU_FAULTS"] = saved_faults
            take_walls.append(time.perf_counter() - t0)
            if (step + 1) % retain_every == 0:
                sync_step_series()
                catalog.retain(bucket, policy, dry_run=False)
            size_series.append((step + 1, bucket_bytes(bucket)))
        sync_step_series()
        step_series.sort(key=lambda r: r.get("step", 0))
        sustained_s = time.perf_counter() - t_begin
        per_minute = steps / sustained_s * 60.0

        with catalog.Catalog(bucket) as cat:
            records = cat.load(job="continuous-bench")
        full_takes = sum(1 for r in records if r.chain_len == 0)
        max_chain_seen = max((r.chain_len for r in records), default=0)

        # Plateau check: once retention has cycled at least twice, the
        # bucket must stop growing with snapshot count. Compare the max of
        # the last quarter against the size right after the SECOND
        # retention pass (the first steady-state point).
        anchor_idx = min(2 * retain_every, len(size_series) - 1)
        anchor = size_series[anchor_idx][1]
        tail = [b for _n, b in size_series[-max(1, len(size_series) // 4):]]
        plateau_ratio = max(tail) / anchor if anchor else float("inf")
        # The retained window itself (worst case: keep_last full snapshots
        # + the in-window deltas) bounds what the bucket may hold.
        window_bound = keep_last * (frozen_bytes + adapter_bytes) * 1.5

        # ---- chain-aware warm restore: restore T-1 cache-warm, then T.
        latest = records[-1].name
        prev = records[-2].name if len(records) > 1 else latest
        os.environ["TORCHSNAPSHOT_TPU_READ_CACHE_DIR"] = cache_dir
        try:
            def restore(name):
                out = {
                    "m": StateDict(
                        **{k: np.zeros_like(v) for k, v in frozen.items()},
                        **{k: np.zeros_like(v) for k, v in adapters.items()},
                    )
                }
                Snapshot(os.path.join(bucket, name)).restore(out)
                return out, dict(snapshot_mod.LAST_RESTORE_STATS)

            _w, warmup_stats = restore(prev)  # populates the cache
            out, warm_stats = restore(latest)
        finally:
            del os.environ["TORCHSNAPSHOT_TPU_READ_CACHE_DIR"]
        warm_origin = warm_stats["attribution"]["origin_bytes"]
        warm_cache = warm_stats["attribution"]["cache_bytes"]
        # The newest step's NEW bytes are its adapters (the frozen
        # backbone dedups along the chain and must come from the cache).
        delta_budget = adapter_bytes * 1.2 + 1e6
        bit_exact = all(
            np.array_equal(out["m"][k], adapters[k]) for k in adapters
        ) and all(np.array_equal(out["m"][k], frozen[k]) for k in frozen)

        # ---- health detectors over the job-lifetime step series.
        anomalies = health.detect_anomalies(
            step_series,
            bucket_bytes=[b for _n, b in size_series],
            window_bound=int(window_bound),
        )
        health.log_anomalies(anomalies)
        timeline = health.render_timeline(step_series, anomalies)
        for line in timeline:
            print(line, file=sys.stderr)

        result = {
            "metric": "sustained_checkpoints_per_minute",
            "value": round(per_minute, 2),
            "unit": "snapshots/min",
            "detail": {
                "steps": steps,
                "keep_last": keep_last,
                "retain_every": retain_every,
                "max_chain_len": max_chain,
                "frozen_mb": round(frozen_bytes / 1e6, 2),
                "adapter_mb": round(adapter_bytes / 1e6, 2),
                "sustained_wall_s": round(sustained_s, 2),
                "take_wall_p50_s": round(sorted(take_walls)[len(take_walls) // 2], 4),
                "take_wall_max_s": round(max(take_walls), 4),
                "bucket_bytes_series": size_series,
                "bucket_bytes_final": size_series[-1][1],
                "bucket_bytes_anchor": anchor,
                "plateau_ratio": round(plateau_ratio, 3),
                "window_bound_bytes": int(window_bound),
                "records_live": len(records),
                "full_takes_live": full_takes,
                "max_chain_seen": max_chain_seen,
                "step_telemetry": {
                    "expect_anomaly": expect,
                    "fault_step": fault_step,
                    "steps_recorded": len(step_series),
                    "summary": steprecord.summarize_series(step_series),
                    "anomalies": anomalies,
                    "timeline": timeline,
                },
                "warm_restore": {
                    "origin_bytes": int(warm_origin),
                    "cache_bytes": int(warm_cache),
                    "delta_budget_bytes": int(delta_budget),
                    "warmup_origin_bytes": int(
                        warmup_stats["attribution"]["origin_bytes"]
                    ),
                    "bit_exact": bool(bit_exact),
                },
            },
        }

        problems = []
        if steps >= 2 * retain_every and plateau_ratio > 1.25:
            problems.append(
                f"bucket did not plateau: ratio {plateau_ratio:.2f} > 1.25"
            )
        if size_series[-1][1] > window_bound:
            problems.append(
                f"bucket {size_series[-1][1]} exceeds the retained-window "
                f"bound {int(window_bound)}"
            )
        if warm_origin > delta_budget:
            problems.append(
                f"warm restore read {warm_origin} origin bytes > delta "
                f"budget {int(delta_budget)} (chain-aware cache not engaged)"
            )
        if not bit_exact:
            problems.append("warm restore not bit-exact")
        if max_chain_seen > max_chain:
            problems.append(
                f"recorded chain {max_chain_seen} exceeds max_chain_len "
                f"{max_chain}"
            )
        telemetry_on = (
            knobs.is_step_telemetry_enabled()
            and knobs.is_telemetry_artifacts_enabled()
        )
        if telemetry_on and len(step_series) < steps:
            problems.append(
                f"step telemetry recorded {len(step_series)}/{steps} steps "
                "(rollup append is dropping records)"
            )
        kinds = sorted({a["kind"] for a in anomalies})
        if expect == "stall":
            if "stall_spike" not in kinds:
                problems.append(
                    "expected a stall_spike anomaly (injected fault) but "
                    f"detectors saw {kinds or 'none'}"
                )
        elif telemetry_on and anomalies:
            problems.append(
                f"false-positive anomalies on clean run: {kinds}"
            )
        result["detail"]["problems"] = problems
        print(json.dumps(result))
        if problems:
            print(f"FAILED: {problems}", file=sys.stderr)
            sys.exit(1)
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
