"""Elastic reshard bench: N→M restore as a measured, minimal-byte operation.

The resharding engine (``io_preparers/sharded_array.py``) restores a
snapshot across changed mesh shapes, axis orders, and device counts; this
bench makes that a MEASURED claim instead of a correctness-only one:

- **Matrix cells** (fresh process per side — the device count is fixed at
  backend init, so save and restore each get their own child process):
  ``8to4``, ``4to8``, ``8to4_transposed`` (mesh axes swapped), and
  ``4to8_replicated`` (the restored mesh replicates one axis). Every cell
  asserts bit-exactness, then reports reshard wall, reshard GB/s, origin
  bytes vs **theoretical overlap bytes** (the union of saved-shard rows
  the targets actually overlap — what a minimal-byte reshard must fetch;
  ratio target ≤ 1.1×, the slack being hash-chunk alignment), and the
  per-object origin/peer/cache attribution from
  ``snapshot.LAST_RESTORE_STATS["attribution"]``.
- **Fleet leg** (``RESHARD_BENCH_FLEET_KS``, default ``2``): K real ranks
  (jax.distributed on CPU, 2 devices each) restore onto a mesh whose
  leading axis REPLICATES across processes — every rank needs every byte,
  the replicated-overlap case. Asserts every hash chunk is origin-fetched
  exactly ONCE fleet-wide (total origin bytes == one payload, not K×) and
  every peer-received chunk verified.

One JSON line on stdout; progress on stderr.

  python benchmarks/reshard/main.py                    # 64 MB matrix + K=2
  RESHARD_BENCH_MB=8 RESHARD_BENCH_FLEET_KS=2,4,8 \
  python benchmarks/reshard/main.py                    # fleet sweep
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

COLS = 4096  # fp32 -> 16 KiB rows
GRAIN = int(os.environ.get("RESHARD_BENCH_GRAIN", str(1 << 20)))

# name -> (save_devices, save_mesh, save_axes, save_spec,
#          restore_devices, restore_mesh, restore_axes, restore_spec)
CELLS = {
    "2to4": (2, (2,), ("x",), ("x",), 4, (4,), ("x",), ("x",)),
    "8to4": (8, (8,), ("x",), ("x",), 4, (4,), ("x",), ("x",)),
    "4to8": (4, (4,), ("x",), ("x",), 8, (8,), ("x",), ("x",)),
    "8to4_transposed": (
        8, (4, 2), ("a", "b"), ("a", "b"), 4, (2, 2), ("a", "b"), ("b", "a"),
    ),
    "4to8_replicated": (
        4, (4,), ("x",), ("x",), 8, (4, 2), ("a", "b"), ("a",),
    ),
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _host(rows: int):
    import numpy as np

    # Deterministic content both child processes can regenerate.
    return (
        np.arange(rows * COLS, dtype=np.uint32)
        .reshape(rows, COLS)
        .view(np.float32)
    )


def _place(host, mesh_shape, axes, spec_axes, n_devices):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.array(jax.devices()[:n_devices]).reshape(mesh_shape)
    mesh = Mesh(devices, axes)
    spec = P(*spec_axes) if spec_axes else P()
    return jax.device_put(host, NamedSharding(mesh, spec))


def child_take(cell: str, rows: int, root: str) -> None:
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.utils import knobs

    n, mesh_shape, axes, spec = CELLS[cell][:4]
    arr = _place(_host(rows), mesh_shape, axes, spec, n)
    with knobs.override_hash_chunk_bytes(GRAIN):
        Snapshot.take(os.path.join(root, "ckpt"), {"m": StateDict(x=arr)})


def child_restore(cell: str, rows: int, root: str, out_path: str) -> None:
    import jax
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu import snapshot as snapshot_mod
    from torchsnapshot_tpu.io_preparers.sharded_array import (
        index_to_offsets_sizes,
        overlap_row_intervals,
    )
    from torchsnapshot_tpu.serialization import string_to_dtype

    m, mesh_shape, axes, spec = CELLS[cell][4:]
    host = _host(rows)
    tgt_arr = _place(
        np.zeros_like(host), mesh_shape, axes, spec, m
    )
    path = os.path.join(root, "ckpt")
    entry = Snapshot(path).get_manifest()["0/m/x"]

    # Theoretical overlap bytes: for every saved shard, the union of row
    # intervals THIS process's target shards overlap (row-covering — the
    # contiguity unit a byte-range read can fetch), pre-alignment.
    sharding = tgt_arr.sharding
    rects, seen = [], set()
    for d in sharding.addressable_devices:
        idx = sharding.addressable_devices_indices_map(tuple(host.shape))[d]
        off, sz = index_to_offsets_sizes(idx, host.shape)
        if tuple(off) not in seen:
            seen.add(tuple(off))
            rects.append((off, sz))
    theoretical = 0
    for shard in entry.shards:
        itemsize = string_to_dtype(shard.tensor.dtype).itemsize
        row_bytes = itemsize * int(np.prod(shard.sizes[1:]))
        for b, e in overlap_row_intervals(shard.offsets, shard.sizes, rects):
            theoretical += (e - b) * row_bytes

    tgt = StateDict(x=tgt_arr)
    t0 = time.perf_counter()
    Snapshot(path).restore({"m": tgt})
    wall_s = time.perf_counter() - t0
    for shard in tgt["x"].addressable_shards:
        assert np.array_equal(
            np.asarray(shard.data).view(np.uint8),
            host[shard.index].view(np.uint8),
        ), f"cell {cell}: restore NOT bit-exact at {shard.index}"
    attr = snapshot_mod.LAST_RESTORE_STATS["attribution"]
    origin = int(attr["origin_bytes"])
    rec = {
        "cell": cell,
        "payload_gb": round(host.nbytes / 1e9, 4),
        "reshard_wall_s": round(wall_s, 4),
        "reshard_gbps": round(host.nbytes / 1e9 / max(wall_s, 1e-9), 4),
        "origin_bytes": origin,
        "theoretical_overlap_bytes": theoretical,
        "origin_ratio": round(origin / max(theoretical, 1), 4),
        "attribution": {k: int(v) for k, v in attr.items()},
        "bit_exact": True,
    }
    assert rec["origin_ratio"] <= 1.1, rec
    with open(out_path, "w") as f:
        json.dump(rec, f)


def _spawn(args, n_devices: int, timeout: int = 600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + args,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"child {args} failed:\n{proc.stderr[-3000:]}")


def run_cell(cell: str, total_mb: float) -> dict:
    spec = CELLS[cell]
    rows = max(16, int(total_mb * 1e6 / 4 / COLS))
    rows -= rows % 16  # divisible by every mesh extent used here
    root = tempfile.mkdtemp(prefix=f"tss_reshard_{cell}_")
    out_path = os.path.join(root, "cell.json")
    try:
        _spawn(["--take", cell, str(rows), root], spec[0])
        _spawn(["--restore", cell, str(rows), root, out_path], spec[4])
        with open(out_path) as f:
            return json.load(f)
    finally:
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# Fleet leg: replicated-overlap chunks fetched exactly once across K ranks.
# ---------------------------------------------------------------------------

def _fleet_worker(
    rank: int, world_size: int, shared: str, rows: int, grain: int
) -> None:
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu import swarm as swarm_mod
    from torchsnapshot_tpu.utils import knobs

    host = _host(rows)
    path = os.path.join(shared, "ckpt")
    devices = np.array(jax.devices())  # world_size * 2 global devices
    src = jax.make_array_from_callback(
        host.shape,
        NamedSharding(Mesh(devices, ("x",)), P(None, "x")),
        lambda idx: host[idx],
    )
    with knobs.override_hash_chunk_bytes(grain):
        Snapshot.take(path, {"m": StateDict(x=src)})

    # Leading mesh axis spans processes and is NOT in the spec: every
    # process needs every byte — the replicated-overlap case.
    mesh = Mesh(devices.reshape(world_size, 2), ("a", "b"))
    tgt_arr = jax.make_array_from_callback(
        host.shape,
        NamedSharding(mesh, P(None, "b")),
        lambda idx: np.zeros_like(host)[idx],
    )
    tgt = StateDict(x=tgt_arr)
    with knobs.override_swarm_restore(True):
        Snapshot(path).restore({"m": tgt})
    for shard in tgt["x"].addressable_shards:
        assert np.array_equal(np.asarray(shard.data), host[shard.index])
    d = dict(swarm_mod.LAST_RESTORE_SWARM)
    assert d["peer_chunks_verified"] == d["chunks_peer"], d
    with open(os.path.join(shared, f"fleet_diag_{rank}.json"), "w") as f:
        json.dump(
            {
                "origin_reads": d["origin_reads"],
                "origin_bytes": d["origin_bytes"],
                "peer_bytes": d["peer_bytes"],
                "chunks": d["chunks"],
            },
            f,
        )


def run_fleet(k: int, total_mb: float) -> dict:
    from torchsnapshot_tpu.test_utils import run_with_processes

    rows = max(16, int(total_mb * 1e6 / 4 / COLS))
    rows -= rows % 16
    payload = rows * COLS * 4
    # The save spreads 2K column shards; each must span several hash
    # chunks or there is no v2 grid and the swarm (correctly) declines.
    grain = max(16384, min(GRAIN, payload // (2 * k) // 2))
    shared = tempfile.mkdtemp(prefix=f"tss_reshard_fleet{k}_")
    try:
        run_with_processes(
            _fleet_worker,
            nproc=k,
            init_jax_distributed=True,
            args=(shared, rows, grain),
            timeout_s=600.0,
        )
        diags = [
            json.load(open(os.path.join(shared, f"fleet_diag_{r}.json")))
            for r in range(k)
        ]
    finally:
        shutil.rmtree(shared, ignore_errors=True)
    assert diags[0]["chunks"] > 0, (
        f"K={k}: the need-aware swarm never engaged (no v2 chunk grids?)"
    )
    all_reads = [tuple(x) for d in diags for x in d["origin_reads"]]
    assert len(all_reads) == len(set(all_reads)), (
        f"K={k}: a chunk was origin-fetched twice"
    )
    total_origin = sum(d["origin_bytes"] for d in diags)
    ratio = total_origin / payload
    assert ratio <= 1.1, (k, total_origin, payload)
    return {
        "k": k,
        "payload_gb": round(payload / 1e9, 4),
        "fleet_origin_bytes": total_origin,
        "origin_ratio_vs_one_payload": round(ratio, 4),
        "chunks": diags[0]["chunks"],
        "peer_bytes_total": sum(d["peer_bytes"] for d in diags),
        "per_rank_origin_reads": [len(d["origin_reads"]) for d in diags],
    }


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--take":
        child_take(sys.argv[2], int(sys.argv[3]), sys.argv[4])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--restore":
        child_restore(sys.argv[2], int(sys.argv[3]), sys.argv[4], sys.argv[5])
        return
    total_mb = float(os.environ.get("RESHARD_BENCH_MB", "64"))
    fleet_mb = float(os.environ.get("RESHARD_BENCH_FLEET_MB", "8"))
    cells = [
        c
        for c in os.environ.get(
            "RESHARD_BENCH_CELLS", ",".join(CELLS)
        ).split(",")
        if c.strip()
    ]
    fleet_ks = [
        int(x)
        for x in os.environ.get("RESHARD_BENCH_FLEET_KS", "2").split(",")
        if x.strip()
    ]
    matrix = []
    for cell in cells:
        rec = run_cell(cell, total_mb)
        matrix.append(rec)
        log(f"{cell}: {rec}")
    fleet = []
    for k in fleet_ks:
        rec = run_fleet(k, fleet_mb)
        fleet.append(rec)
        log(f"fleet K={k}: {rec}")
    worst_ratio = max(r["origin_ratio"] for r in matrix)
    print(
        json.dumps(
            {
                "metric": "reshard_origin_ratio_worst",
                "value": worst_ratio,
                "unit": "x_theoretical_overlap",
                "detail": {
                    "matrix_mb": total_mb,
                    "grain": GRAIN,
                    "cells": matrix,
                    "reshard_wall_s_max": max(
                        r["reshard_wall_s"] for r in matrix
                    ),
                    "reshard_gbps_min": min(
                        r["reshard_gbps"] for r in matrix
                    ),
                    "fleet": fleet,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
