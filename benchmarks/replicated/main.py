"""Replicated-model save benchmark (reference ``benchmarks/ddp/main.py``).

The reference's headline: a 20 GB DDP (fully replicated) model saved by N
ranks in parallel vs one ``torch.save``. TPU equivalent: a replicated param
set saved by N processes, write load partitioned across them; baseline is a
single-process pickle of the same bytes.

  python benchmarks/replicated/main.py --gb 2 --nproc 4
"""

import argparse
import os
import pickle
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def _make_state(total_gb: float):
    n = max(1, int(total_gb * 1e9 / (64 * 1024 * 1024)))
    rng = np.random.default_rng(0)
    return {
        f"p{i}": rng.standard_normal(16 * 1024 * 1024).astype(np.float32)
        for i in range(n)
    }


def _worker(rank: int, world_size: int, shared: str, total_gb: float) -> None:
    from torchsnapshot_tpu import Snapshot, StateDict

    state = StateDict(**_make_state(total_gb))
    t0 = time.perf_counter()
    Snapshot.take(os.path.join(shared, "ckpt"), {"m": state}, replicated=["m/*"])
    if rank == 0:
        elapsed = time.perf_counter() - t0
        print(
            f"[torchsnapshot_tpu] {total_gb:.1f} GB replicated, "
            f"{world_size} procs: {elapsed:.2f}s ({total_gb / elapsed:.2f} GB/s)"
        )


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=1.0)
    parser.add_argument("--nproc", type=int, default=4)
    args = parser.parse_args()

    state = _make_state(args.gb)
    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        with open(os.path.join(tmp, "baseline.pkl"), "wb") as f:
            pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
        base = time.perf_counter() - t0
        print(f"[pickle baseline] {args.gb:.1f} GB: {base:.2f}s "
              f"({args.gb / base:.2f} GB/s)")

    from torchsnapshot_tpu.test_utils import run_with_processes

    with tempfile.TemporaryDirectory() as shared:
        run_with_processes(
            _worker, nproc=args.nproc, args=(shared, args.gb), timeout_s=600
        )


if __name__ == "__main__":
    main()
