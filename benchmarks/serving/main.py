"""Serving-scale read-path benchmark: K replicas cold-start from ONE snapshot.

Production inference restores the same snapshot on a fleet; the read path's
job is to make that cost 1x the snapshot, not Kx. This harness simulates a
fleet of K replicas and measures the three serving-path mechanisms:

- **read-through cache** (``TORCHSNAPSHOT_TPU_READ_CACHE_DIR``): replicas
  sharing a local cache volume restore with p50/p99 wall times reported for
  cache off vs on; with the cache on, every replica after the first reads
  **0 bytes from origin storage** (asserted from per-restore telemetry);
- **broadcast restore** (``TORCHSNAPSHOT_TPU_BCAST_RESTORE``): K real
  processes restore replicated entries with broadcast off vs on; with it
  on, each replicated object is read from origin by **exactly one rank**
  (asserted from ``bcast.LAST_RESTORE_BCAST`` gathered across ranks);
- **lazy partial reads**: ``read_object`` of one tower's manifest subtree
  fetches only that subtree's bytes (asserted against the tower/total
  payload ratio from storage read counters);
- **swarm restore** (``TORCHSNAPSHOT_TPU_SWARM_RESTORE``): K real ranks
  cold-restore ONE replicated object too big for broadcast via the
  chunk-granular swarm, at K ∈ ``SERVING_BENCH_SWARM_KS`` (default 2,4,8);
  asserted per K: every chunk origin-read by **exactly one rank**
  fleet-wide, **total origin bytes ≤ 1.1× one snapshot independent of K**,
  and every peer-received chunk verified against the sidecar v2 grid.

One JSON line on stdout; progress on stderr.

  python benchmarks/serving/main.py                       # ~64 MB, K=8
  SERVING_BENCH_MB=8 SERVING_BENCH_REPLICAS=3 \
  SERVING_BENCH_BCAST=0 python benchmarks/serving/main.py  # fast smoke
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

from torchsnapshot_tpu import Snapshot, StateDict, telemetry  # noqa: E402
from torchsnapshot_tpu import snapshot as snapshot_mod  # noqa: E402
from torchsnapshot_tpu.utils import knobs  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _pct(values, q: float) -> float:
    s = sorted(values)
    if not s:
        return 0.0
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


def build_state(total_mb: float, towers: int = 4, seed: int = 0) -> StateDict:
    """``towers`` equal towers of float32 layers — the lazy-read unit."""
    rng = np.random.default_rng(seed)
    per_tower = max(1, int(total_mb * 1e6 / towers / 4))
    model = {}
    for t in range(towers):
        model[f"tower_{t}"] = {
            "w": rng.standard_normal(per_tower, dtype=np.float32)
        }
    return StateDict(model=model, step=0)


def fresh_targets(total_mb: float, towers: int = 4) -> StateDict:
    per_tower = max(1, int(total_mb * 1e6 / towers / 4))
    model = {
        f"tower_{t}": {"w": np.zeros(per_tower, dtype=np.float32)}
        for t in range(towers)
    }
    return StateDict(model=model, step=0)


def restore_once(path: str, total_mb: float) -> dict:
    """One replica's cold restore; returns wall + origin-byte accounting."""
    tm = telemetry.Telemetry()
    targets = fresh_targets(total_mb)
    t0 = time.perf_counter()
    Snapshot(path).restore({"app": targets}, _telemetry=tm)
    wall = time.perf_counter() - t0
    m = tm.metrics.as_dict()
    origin = sum(
        v for k, v in m.items() if k.endswith(".read_bytes") and k.startswith("storage.")
    )
    return {
        "wall_s": wall,
        "origin_bytes": int(origin),
        "cache_hits": int(m.get("cache.hits", 0)),
        "cache_misses": int(m.get("cache.misses", 0)),
    }


def run_cache_leg(origin_root: str, total_mb: float, replicas: int) -> dict:
    """K sequential replica cold-starts, cache off vs on (shared local
    cache volume — the co-hosted-replicas serving shape)."""
    path = os.path.join(origin_root, "snap")
    out = {}
    for mode in ("off", "on"):
        walls = []
        records = []
        if mode == "on":
            cache_dir = tempfile.mkdtemp(prefix="tss_serving_cache_")
            ctx = knobs.override_read_cache_dir(cache_dir)
        else:
            cache_dir = None
            ctx = None
        try:
            if ctx is not None:
                ctx.__enter__()
            for _ in range(replicas):
                rec = restore_once(path, total_mb)
                walls.append(rec["wall_s"])
                records.append(rec)
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
            if cache_dir:
                shutil.rmtree(cache_dir, ignore_errors=True)
        warm_origin = sum(r["origin_bytes"] for r in records[1:])
        out[mode] = {
            "replicas": replicas,
            "restore_p50_s": round(_pct(walls, 0.50), 4),
            "restore_p99_s": round(_pct(walls, 0.99), 4),
            "cold_origin_bytes": records[0]["origin_bytes"],
            "warm_origin_bytes_total": warm_origin,
            "total_origin_bytes": sum(r["origin_bytes"] for r in records),
        }
        log(f"cache {mode}: {out[mode]}")
    assert out["on"]["warm_origin_bytes_total"] == 0, (
        "cache-on repeat restores must read 0 bytes from origin: "
        f"{out['on']}"
    )
    return out


def _bcast_worker(rank: int, world: int, path: str, total_mb: float, result_path: str) -> None:
    """One fleet rank: take a replicated snapshot together, then restore it
    with broadcast off and on, gathering walls + broadcast records."""
    from torchsnapshot_tpu import bcast
    from torchsnapshot_tpu.parallel.coordinator import get_coordinator

    state = build_state(total_mb, seed=7)
    Snapshot.take(path, {"app": state}, replicated=["app/*"])
    results = {}
    for mode in ("off", "on"):
        targets = fresh_targets(total_mb)
        with knobs.override_broadcast_restore(mode == "on"):
            t0 = time.perf_counter()
            Snapshot(path).restore({"app": targets})
            wall = time.perf_counter() - t0
        d = dict(bcast.LAST_RESTORE_BCAST)
        coord = get_coordinator()
        gathered = coord.all_gather_object(
            {
                "wall_s": wall,
                "origin_reads": d.get("origin_reads", []),
                "recv_bytes": d.get("recv_bytes", 0),
                "origin_bytes": d.get("origin_bytes", 0),
            }
        )
        if rank == 0:
            walls = [g["wall_s"] for g in gathered]
            all_origin = [p for g in gathered for p in g["origin_reads"]]
            results[mode] = {
                "ranks": world,
                "restore_p50_s": round(_pct(walls, 0.50), 4),
                "restore_p99_s": round(_pct(walls, 0.99), 4),
                "origin_reads_total": len(all_origin),
                "origin_reads_unique": len(set(all_origin)),
                "recv_bytes_total": sum(g["recv_bytes"] for g in gathered),
            }
    if rank == 0:
        on = results["on"]
        assert on["origin_reads_total"] == on["origin_reads_unique"], (
            f"broadcast restore read a replicated object from more than one "
            f"rank: {results}"
        )
        assert on["origin_reads_total"] > 0 and on["recv_bytes_total"] > 0, (
            f"broadcast restore never engaged: {results}"
        )
        with open(result_path, "w") as f:
            json.dump(results, f)


def run_bcast_leg(total_mb: float, ranks: int) -> dict:
    from torchsnapshot_tpu.test_utils import run_with_processes

    root = tempfile.mkdtemp(prefix="tss_serving_bcast_")
    result_path = os.path.join(root, "results.json")
    try:
        run_with_processes(
            _bcast_worker,
            nproc=ranks,
            args=(os.path.join(root, "snap"), total_mb, result_path),
            timeout_s=600.0,
        )
        with open(result_path) as f:
            results = json.load(f)
        for mode, rec in results.items():
            log(f"broadcast {mode}: {rec}")
        return results
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _swarm_worker(
    rank: int, world: int, path: str, total_mb: float, result_path: str
) -> None:
    """One fleet rank of the swarm leg: take ONE replicated object too big
    for broadcast, cold-restore it via the chunk swarm, and gather the
    per-rank swarm records so rank 0 can assert the headline invariants."""
    from torchsnapshot_tpu import swarm as swarm_mod
    from torchsnapshot_tpu.parallel.coordinator import get_coordinator

    nbytes = int(total_mb * 1e6)
    arr = np.frombuffer(
        np.random.default_rng(11).bytes(nbytes), dtype=np.uint8
    ).copy()
    # One big replicated array; a small grain keeps the chunk grid wide
    # enough that every rank gets assigned chunks even at K=8.
    grain = max(64 * 1024, nbytes // 64)
    with knobs.override_hash_chunk_bytes(grain):
        Snapshot.take(path, {"app": StateDict(w=arr)}, replicated=["app/*"])
    tgt = StateDict(w=np.zeros(nbytes, np.uint8))
    # Cap broadcast far below the object so mode selection picks swarm.
    with knobs.override_swarm_restore(True), knobs.override_broadcast_max_bytes(
        64 * 1024
    ):
        t0 = time.perf_counter()
        Snapshot(path).restore({"app": tgt})
        wall = time.perf_counter() - t0
    assert np.array_equal(tgt["w"], arr), "swarm restore not bit-exact"
    d = dict(swarm_mod.LAST_RESTORE_SWARM)
    coord = get_coordinator()
    gathered = coord.all_gather_object(
        {
            "wall_s": wall,
            "origin_reads": [list(x) for x in d["origin_reads"]],
            "origin_bytes": d["origin_bytes"],
            "peer_bytes": d["peer_bytes"],
            "chunks": d["chunks"],
            "chunks_peer": d["chunks_peer"],
            "peer_chunks_verified": d["peer_chunks_verified"],
        }
    )
    if rank == 0:
        walls = [g["wall_s"] for g in gathered]
        all_reads = [tuple(x) for g in gathered for x in g["origin_reads"]]
        total_origin = sum(g["origin_bytes"] for g in gathered)
        rec = {
            "ranks": world,
            "restore_p50_s": round(_pct(walls, 0.50), 4),
            "restore_p99_s": round(_pct(walls, 0.99), 4),
            "chunks": gathered[0]["chunks"],
            "origin_chunk_reads_total": len(all_reads),
            "origin_chunk_reads_unique": len(set(all_reads)),
            "origin_bytes_total": total_origin,
            "origin_bytes_vs_snapshot": round(total_origin / nbytes, 3),
            "peer_bytes_total": sum(g["peer_bytes"] for g in gathered),
            "peer_chunks_total": sum(g["chunks_peer"] for g in gathered),
            "peer_chunks_verified": sum(
                g["peer_chunks_verified"] for g in gathered
            ),
        }
        # The headline asserts: every chunk origin-read EXACTLY once
        # fleet-wide, total origin bytes ≈ one snapshot independent of K,
        # every peer-received chunk verified against the sidecar grid.
        assert (
            rec["origin_chunk_reads_total"]
            == rec["origin_chunk_reads_unique"]
            == rec["chunks"]
        ), rec
        assert rec["origin_bytes_total"] <= 1.1 * nbytes, rec
        assert rec["peer_chunks_verified"] == rec["peer_chunks_total"] > 0, rec
        with open(result_path, "w") as f:
            json.dump(rec, f)


def run_swarm_leg(total_mb: float, ranks_list) -> dict:
    """Chunk-swarm cold start at K∈ranks_list: origin bytes must stay ≈ one
    snapshot (and cold-start p99 ≈ flat) as the fleet grows — the curve
    broadcast restore cannot produce above its payload cap."""
    from torchsnapshot_tpu.test_utils import run_with_processes

    out = {}
    for ranks in ranks_list:
        root = tempfile.mkdtemp(prefix="tss_serving_swarm_")
        result_path = os.path.join(root, "results.json")
        try:
            run_with_processes(
                _swarm_worker,
                nproc=ranks,
                args=(os.path.join(root, "snap"), total_mb, result_path),
                timeout_s=600.0,
            )
            with open(result_path) as f:
                rec = json.load(f)
            out[str(ranks)] = rec
            log(f"swarm K={ranks}: {rec}")
        finally:
            shutil.rmtree(root, ignore_errors=True)
    # Flat-in-K: origin bytes at the largest K stay within 10% of one
    # snapshot, same as the smallest K (asserted per K above already).
    return out


def run_lazy_leg(origin_root: str, total_mb: float) -> dict:
    """Read ONE tower's subtree; origin bytes must track the tower's size,
    not the snapshot's."""
    path = os.path.join(origin_root, "snap")
    tm = telemetry.Telemetry()
    prev = telemetry.activate(tm)
    t0 = time.perf_counter()
    try:
        sub = Snapshot(path).read_object("0/app/model/tower_0")
    finally:
        telemetry.deactivate(tm, prev)
    wall = time.perf_counter() - t0
    tower_bytes = int(sub["w"].nbytes)
    m = tm.metrics.as_dict()
    origin = sum(
        v for k, v in m.items() if k.endswith(".read_bytes") and k.startswith("storage.")
    )
    total_bytes = int(total_mb * 1e6)
    rec = {
        "wall_s": round(wall, 4),
        "subtree_bytes": tower_bytes,
        "origin_bytes": int(origin),
        "snapshot_payload_bytes": total_bytes,
        "overhead_ratio": round(origin / max(tower_bytes, 1), 3),
    }
    # Subtree bytes + metadata/sidecar overhead — but never the other towers
    # (which would roughly quadruple the bytes here).
    assert origin < tower_bytes + total_bytes / 2, (
        f"lazy read fetched beyond its subtree: {rec}"
    )
    log(f"lazy subtree read: {rec}")
    return rec


def main() -> None:
    total_mb = float(os.environ.get("SERVING_BENCH_MB", "64"))
    replicas = int(os.environ.get("SERVING_BENCH_REPLICAS", "8"))
    bcast_on = os.environ.get("SERVING_BENCH_BCAST", "1") not in ("0", "false")
    bcast_ranks = int(os.environ.get("SERVING_BENCH_BCAST_RANKS", "8"))
    swarm_on = os.environ.get("SERVING_BENCH_SWARM", "1") not in ("0", "false")
    swarm_ks = [
        int(k)
        for k in os.environ.get("SERVING_BENCH_SWARM_KS", "2,4,8").split(",")
        if k.strip()
    ]

    origin_root = tempfile.mkdtemp(prefix="tss_serving_")
    try:
        state = build_state(total_mb)
        t0 = time.perf_counter()
        Snapshot.take(os.path.join(origin_root, "snap"), {"app": state})
        log(f"took {total_mb:.0f} MB snapshot in {time.perf_counter() - t0:.2f}s")

        lazy = run_lazy_leg(origin_root, total_mb)
        cache = run_cache_leg(origin_root, total_mb, replicas)
        bcast_res = run_bcast_leg(total_mb, bcast_ranks) if bcast_on else {}
        swarm_res = run_swarm_leg(total_mb, swarm_ks) if swarm_on else {}

        print(
            json.dumps(
                {
                    "metric": "serving_cold_start_restore_p50",
                    "value": cache["on"]["restore_p50_s"],
                    "unit": "s",
                    "detail": {
                        "payload_mb": total_mb,
                        "replicas": replicas,
                        "cache": cache,
                        "broadcast": bcast_res,
                        "swarm": swarm_res,
                        "lazy_subtree": lazy,
                        "restore_stats": {
                            k: v
                            for k, v in snapshot_mod.LAST_RESTORE_STATS.items()
                            if k != "bcast"
                        },
                        "env": {"knobs": knobs.env_fingerprint()},
                    },
                }
            )
        )
    finally:
        shutil.rmtree(origin_root, ignore_errors=True)


if __name__ == "__main__":
    main()
