"""Async-take stall decomposition at world size > 1.

The headline metric of the framework is the training stall of
``Snapshot.async_take`` — planning plus mutable-host-state capture, NOT
checkpoint size (device bytes drain in the background). This harness measures
that stall *with the sharded path fully engaged*: N spawned processes form a
real multi-process jax CPU runtime (2 virtual devices each, the analogue of
the reference's multi-rank benches on gloo), a train-state-shaped pytree is
sharded over the global (dp, tp) mesh, and each rank reports its stall and
its per-phase decomposition (key gather, prepare, partition, manifest
gather, capture/device-fork) from ``torchsnapshot_tpu.snapshot``'s phase
timings.

  python benchmarks/stall/main.py --nproc 4 --mb-per-rank 64 --steps 3

Reference model: the stall claim in ``BASELINE.json`` (7B FSDP-style model,
<5 s stall); the reference measures coordination overhead only implicitly in
``benchmarks/ddp/`` wall times.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _worker(rank: int, world_size: int, shared: str, mb_per_rank: int, steps: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu import snapshot as snapshot_mod

    devices = np.array(jax.devices()).reshape(world_size, -1)
    mesh = Mesh(devices, ("dp", "tp"))
    n_dev = devices.size

    # Train-state shape: params sharded over tp, adam-style moments likewise,
    # plus a replicated scalar step and per-rank host progress.
    total_elems = mb_per_rank * world_size * 1024 * 1024 // 4 // 3
    dim = int(np.sqrt(total_elems / 4))
    dim = max(n_dev, dim - dim % n_dev)
    # Same key on every process: device_put of a multi-process global array
    # requires identical host values everywhere.
    key = jax.random.PRNGKey(0)

    def mk(spec):
        return jax.device_put(
            jax.random.normal(key, (dim, 4 * dim), dtype=jnp.float32),
            NamedSharding(mesh, spec),
        )

    params = mk(P("dp", "tp"))
    mu = mk(P("dp", "tp"))
    nu = mk(P("dp", "tp"))
    app = {
        "train": StateDict(params=params, mu=mu, nu=nu, step=0),
        "progress": StateDict(rank=rank),
    }

    stalls = []
    phase_sums: dict = {}
    for step in range(steps):
        path = os.path.join(shared, f"ckpt_{step}")
        t0 = time.perf_counter()
        pending = Snapshot.async_take(path, app, replicated=["train/step"])
        stall = time.perf_counter() - t0
        pending.wait()
        stalls.append(stall)
        for k, v in getattr(snapshot_mod, "LAST_TAKE_PHASES", {}).items():
            phase_sums.setdefault(k, []).append(v)

    # First take pays one-time costs (jit warmup, pool spinup): report both.
    result = {
        "rank": rank,
        "world_size": world_size,
        "devices": n_dev,
        "bytes_per_rank": int(3 * dim * 4 * dim * 4 / world_size),
        "stall_first_s": round(stalls[0], 4),
        "stall_steady_s": round(min(stalls[1:]) if len(stalls) > 1 else stalls[0], 4),
        "phases_last_s": {k: round(v[-1], 4) for k, v in phase_sums.items()},
    }
    with open(os.path.join(shared, f"result_{rank}.json"), "w") as f:
        json.dump(result, f)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nproc", type=int, default=4)
    parser.add_argument("--mb-per-rank", type=int, default=64)
    parser.add_argument("--steps", type=int, default=3)
    args = parser.parse_args()

    from torchsnapshot_tpu.test_utils import run_with_processes

    with tempfile.TemporaryDirectory() as shared:
        run_with_processes(
            _worker,
            nproc=args.nproc,
            init_jax_distributed=True,
            args=(shared, args.mb_per_rank, args.steps),
            timeout_s=900,
        )
        for rank in range(args.nproc):
            with open(os.path.join(shared, f"result_{rank}.json")) as f:
                print(json.dumps(json.load(f)))


if __name__ == "__main__":
    main()
