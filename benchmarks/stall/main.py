"""Async-take stall decomposition + coordination-cost model at world > 1.

The headline metric of the framework is the training stall of
``Snapshot.async_take`` — planning plus mutable-host-state capture, NOT
checkpoint size (device bytes drain in the background). This harness measures
that stall *with the sharded path fully engaged*: N spawned processes form a
real multi-process jax CPU runtime (2 virtual devices each, the analogue of
the reference's multi-rank benches on gloo), a train-state-shaped pytree is
sharded over the global (dp, tp) mesh, and each rank reports its stall, its
per-phase decomposition, and — new in round 3 — its **store round-trip
counts** per take from ``parallel.store.get_op_counts``.

Why round-trips: on this 1-vCPU host, wall time at world 8 confounds
coordination cost with CPU time-slicing; the round-trip count is the
confound-free quantity. Steady-state takes hit the cross-take plan cache
(``take_plan.py``) and issue a CONSTANT number of round-trips per rank
regardless of world size; first takes pay O(world) on rank 0's gathers. The
``--sweep`` mode runs worlds {1,2,4,8}, verifies the constant-steady-state
property, and projects the v5e-256 stall as
``roundtrips x per-op latency`` — a calculation, not an extrapolated wall
time (VERDICT round 2, items 1 and 8).

  python benchmarks/stall/main.py --nproc 4 --mb-per-rank 64 --steps 3
  python benchmarks/stall/main.py --sweep
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _worker(
    rank: int,
    world_size: int,
    shared: str,
    mb_per_rank: int,
    steps: int,
    plan_cache: bool,
) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu import snapshot as snapshot_mod
    from torchsnapshot_tpu.parallel import store as store_mod
    from torchsnapshot_tpu.utils import knobs

    devices = np.array(jax.devices()).reshape(world_size, -1)
    mesh = Mesh(devices, ("dp", "tp"))
    n_dev = devices.size

    # Train-state shape: params sharded over tp, adam-style moments likewise,
    # plus a replicated scalar step and per-rank host progress.
    total_elems = mb_per_rank * world_size * 1024 * 1024 // 4 // 3
    dim = int(np.sqrt(total_elems / 4))
    dim = max(n_dev, dim - dim % n_dev)
    # Same key on every process: device_put of a multi-process global array
    # requires identical host values everywhere.
    key = jax.random.PRNGKey(0)

    def mk(spec):
        return jax.device_put(
            jax.random.normal(key, (dim, 4 * dim), dtype=jnp.float32),
            NamedSharding(mesh, spec),
        )

    params = mk(P("dp", "tp"))
    mu = mk(P("dp", "tp"))
    nu = mk(P("dp", "tp"))
    app = {
        "train": StateDict(params=params, mu=mu, nu=nu, step=0),
        "progress": StateDict(rank=rank),
    }

    stalls = []
    phase_sums: dict = {}
    roundtrips = []  # per-take store ops issued by THIS rank during the stall
    ctx = knobs.override_plan_cache(plan_cache)
    with ctx:
        for step in range(steps):
            app["train"]["step"] = step
            path = os.path.join(shared, f"ckpt_{step}")
            store_mod.reset_op_counts()
            t0 = time.perf_counter()
            pending = Snapshot.async_take(path, app, replicated=["train/step"])
            stall = time.perf_counter() - t0
            # Main thread only: the background commit thread's barrier ops
            # would otherwise race into the counted window run-to-run.
            ops = store_mod.get_op_counts(current_thread_only=True)
            pending.wait()
            stalls.append(stall)
            roundtrips.append(sum(ops.values()))
            for k, v in getattr(snapshot_mod, "LAST_TAKE_PHASES", {}).items():
                phase_sums.setdefault(k, []).append(v)

    # Pod-restart path: restore coordination cost. Restore runs one key
    # gather+broadcast plus a single post-load barrier — constant store
    # round-trips per rank (the round-3 design paid a key all_gather plus a
    # barrier PER KEY: O(keys x world) reads per rank, all added downtime
    # while a preempted pod restarts).
    store_mod.reset_op_counts()
    t0 = time.perf_counter()
    Snapshot(os.path.join(shared, f"ckpt_{steps - 1}")).restore(app)
    restore_wall = time.perf_counter() - t0
    # Exclude "delete": the coordinator's lazy GC of keys posted by the
    # preceding take loop fires inside this window and would report
    # take-dependent backlog as restore coordination cost.
    restore_ops = sum(
        v
        for k, v in store_mod.get_op_counts(current_thread_only=True).items()
        if k != "delete"
    )

    # First take pays one-time costs (jit warmup, pool spinup): report both.
    result = {
        "rank": rank,
        "world_size": world_size,
        "devices": n_dev,
        "plan_cache": plan_cache,
        "bytes_per_rank": int(3 * dim * 4 * dim * 4 / world_size),
        "stall_first_s": round(stalls[0], 4),
        "stall_steady_s": round(min(stalls[1:]) if len(stalls) > 1 else stalls[0], 4),
        "store_roundtrips_first": roundtrips[0],
        "store_roundtrips_steady": min(roundtrips[1:]) if len(roundtrips) > 1 else roundtrips[0],
        "restore_roundtrips": restore_ops,
        "restore_wall_s": round(restore_wall, 4),
        "phases_last_s": {k: round(v[-1], 4) for k, v in phase_sums.items()},
    }
    with open(os.path.join(shared, f"result_{rank}.json"), "w") as f:
        json.dump(result, f)


def _run_world(nproc: int, mb_per_rank: int, steps: int, plan_cache: bool):
    from torchsnapshot_tpu.test_utils import run_with_processes

    with tempfile.TemporaryDirectory() as shared:
        run_with_processes(
            _worker,
            nproc=nproc,
            init_jax_distributed=True,
            args=(shared, mb_per_rank, steps, plan_cache),
            timeout_s=900,
        )
        results = []
        for rank in range(nproc):
            with open(os.path.join(shared, f"result_{rank}.json")) as f:
                results.append(json.load(f))
        return results


def _sweep(mb_per_rank: int, steps: int) -> None:
    """Worlds {1,2,4,8} x {cache on, cache off}: the coordination model.

    Prints one summary JSON with per-world (stall, round-trips) and a
    projected v5e-256 (64-process) steady-state stall computed from the
    round-trip count times the measured per-op store latency.
    """
    from torchsnapshot_tpu.parallel.store import LocalStore

    # Per-op latency probe: LocalStore is in-process (lower bound); the
    # interesting number for the projection is a typical coordination-service
    # RTT on a pod, which the user can override.
    probe = LocalStore()
    t0 = time.perf_counter()
    n_probe = 1000
    for i in range(n_probe):
        probe.set(f"k{i}", b"x")
        probe.get(f"k{i}")
    local_op_latency_s = (time.perf_counter() - t0) / (2 * n_probe)
    # Representative single-digit-ms gRPC RTT for the jax coordination
    # service across a pod's DCN (what a real v5e-256 pays per store op).
    pod_op_latency_s = float(os.environ.get("STALL_POD_OP_LATENCY_S", "0.002"))

    rows = []
    _last_results = {}
    for nproc in (1, 2, 4, 8):
        for plan_cache in (True, False):
            results = _run_world(nproc, mb_per_rank, steps, plan_cache)
            if plan_cache:
                _last_results[nproc] = results
            worst = max(r["stall_steady_s"] for r in results)
            rts = max(r["store_roundtrips_steady"] for r in results)
            rts_first = max(r["store_roundtrips_first"] for r in results)
            rows.append(
                {
                    "world": nproc,
                    "plan_cache": plan_cache,
                    "stall_steady_max_s": worst,
                    "store_roundtrips_steady_max": rts,
                    "store_roundtrips_first_max": rts_first,
                    "restore_roundtrips_max": max(
                        r["restore_roundtrips"] for r in results
                    ),
                }
            )
            print(json.dumps(rows[-1]), flush=True)

    cached = {r["world"]: r for r in rows if r["plan_cache"]}
    uncached = {r["world"]: r for r in rows if not r["plan_cache"]}
    worlds = sorted(cached)
    rt_cached = [cached[w]["store_roundtrips_steady_max"] for w in worlds]
    rt_uncached = [uncached[w]["store_roundtrips_steady_max"] for w in worlds]

    def fit(ys):
        # Least-squares rt = a*world + b. Non-zero ranks are constant under
        # the cache; the max (rank 0, which reads every gather key) is
        # linear in both modes — with a far smaller slope when cached
        # (2 gathers/take vs gathers+all_gathers+per-key barriers).
        n = len(worlds)
        sx = sum(worlds)
        sy = sum(ys)
        sxx = sum(w * w for w in worlds)
        sxy = sum(w * y for w, y in zip(worlds, ys))
        a = (n * sxy - sx * sy) / max(1, (n * sxx - sx * sx))
        return a, (sy - a * sx) / n

    a_c, b_c = fit(rt_cached)
    a_u, b_u = fit(rt_uncached)
    rt_restore = [cached[w]["restore_roundtrips_max"] for w in worlds]
    a_r, b_r = fit(rt_restore)
    nonzero_rank_cached = min(
        min(r["store_roundtrips_steady"] for r in _last_results[w])
        for w in worlds
        if w > 1
    ) if any(w > 1 for w in worlds) else 0
    proj = {
        "local_store_op_latency_s": round(local_op_latency_s, 8),
        "pod_op_latency_s": pod_op_latency_s,
        "worlds": worlds,
        "roundtrips_steady_cached": rt_cached,
        "roundtrips_steady_uncached": rt_uncached,
        "nonzero_rank_roundtrips_steady_cached": nonzero_rank_cached,
        "fit_rt_per_world": {"cached": round(a_c, 2), "uncached": round(a_u, 2)},
        "projected_world64_stall_cached_s": round(
            (a_c * 64 + b_c) * pod_op_latency_s, 4
        ),
        "projected_world64_stall_uncached_s": round(
            (a_u * 64 + b_u) * pod_op_latency_s, 4
        ),
        "projected_world256_stall_cached_s": round(
            (a_c * 256 + b_c) * pod_op_latency_s, 4
        ),
        "projected_world256_stall_uncached_s": round(
            (a_u * 256 + b_u) * pod_op_latency_s, 4
        ),
        # Pod-restart coordination: restore's store round-trips x RTT —
        # what restore ADDS to restart downtime beyond the storage reads.
        "roundtrips_restore": rt_restore,
        "projected_world256_restore_coordination_s": round(
            (a_r * 256 + b_r) * pod_op_latency_s, 4
        ),
        # The 2 ms/op RTT is an assumption, not a measurement; carry the
        # projection across plausible control-plane latencies so the <5 s
        # claim's sensitivity is explicit (VERDICT round 3, weak 6).
        "rtt_sensitivity": {
            f"{rtt * 1000:g}ms": {
                "world256_stall_cached_s": round((a_c * 256 + b_c) * rtt, 4),
                "world256_stall_uncached_s": round((a_u * 256 + b_u) * rtt, 4),
                "world256_restore_coordination_s": round(
                    (a_r * 256 + b_r) * rtt, 4
                ),
            }
            for rtt in (0.002, 0.005, 0.010)
        },
    }
    print(json.dumps({"coordination_model": proj}, indent=2))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nproc", type=int, default=4)
    parser.add_argument("--mb-per-rank", type=int, default=64)
    parser.add_argument("--steps", type=int, default=3)
    parser.add_argument(
        "--no-plan-cache", action="store_true", help="A/B: disable the plan cache"
    )
    parser.add_argument(
        "--sweep",
        action="store_true",
        help="worlds {1,2,4,8} x cache {on,off} + v5e-256 projection",
    )
    args = parser.parse_args()

    if args.sweep:
        _sweep(args.mb_per_rank, args.steps)
        return

    for r in _run_world(
        args.nproc, args.mb_per_rank, args.steps, not args.no_plan_cache
    ):
        print(json.dumps(r))


if __name__ == "__main__":
    main()
