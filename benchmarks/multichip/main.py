"""Per-device drain scaling: drain GB/s vs device count, as a curve.

The MULTICHIP harness (``__graft_entry__.dryrun_multichip``) proves the
checkpoint path composes with an 8-device mesh — but only as a smoke. This
bench promotes it to a first-class scaling measurement (ROADMAP item 1,
"go bigger"): for each device count N it spawns a fresh process with N
devices, shards one large parameter array across a flat ``(N,)`` mesh, and
drives an ``async_take`` whose background drain runs **N per-device D2H
lanes and N per-shard ``write_stream``s concurrently** (transfer lanes
sized to the device count; streaming writes on). The emitted artifact is
the drain-GB/s-vs-device-count curve — the write-side analogue of the
stall trajectory, and the regression surface for "the drain scales with
devices", not just "the drain is fast on one chip".

Fresh process per N: the device count is fixed at backend initialization
(``--xla_force_host_platform_device_count`` on CPU hosts; the first N real
devices otherwise), so the sweep cannot run in one process.

One JSON line on stdout; progress on stderr.

  python benchmarks/multichip/main.py                        # 1,2,4,8 x 256 MB
  MULTICHIP_BENCH_DEVICES=1,2 MULTICHIP_BENCH_MB=32 \
  python benchmarks/multichip/main.py                        # fast smoke
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def child(n_devices: int, total_mb: float, out_path: str) -> None:
    """One sweep cell: N devices, one flat-sharded array, one async_take.
    Runs in a fresh process (the parent set XLA_FLAGS/JAX_PLATFORMS)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.telemetry import aggregate, fleet
    from torchsnapshot_tpu.utils import knobs

    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices, (
        f"wanted {n_devices} devices, backend exposes {len(devices)}"
    )
    mesh = Mesh(np.array(devices), ("all",))
    rows = max(n_devices, int(total_mb * 1e6 / 2 / 16384))
    rows -= rows % n_devices  # evenly shardable
    host = np.arange(rows * 16384, dtype=np.uint16).reshape(rows, 16384)
    arr = jax.device_put(
        host.view(jax.numpy.bfloat16.dtype), NamedSharding(mesh, P("all"))
    )
    jax.block_until_ready(arr)
    payload_gb = arr.nbytes / 1e9

    root = tempfile.mkdtemp(prefix="tss_multichip_")
    try:
        # Per-device transfer lanes + per-shard write_streams: the drain
        # should hold one lane and one storage stream busy per device.
        # Fleet telemetry forced on for the measured drain (single-process
        # cell, so "auto" resolves off): the cell record carries the
        # beacon rollup — engine high-water mark, final phase — beside the
        # throughput numbers.
        with knobs.override_d2h_lanes(max(4, n_devices)), (
            knobs.override_stream_writes(True)
        ), knobs.override_fleet_telemetry("1"), (
            knobs.override_fleet_beacon_s(0.05)
        ):
            fleet.reset()
            # Warmup absorbs compile/native-engine costs outside the
            # measured drain.
            Snapshot.take(os.path.join(root, "warm"), {"m": StateDict(x=arr)})
            t0 = time.perf_counter()
            pending = Snapshot.async_take(
                os.path.join(root, "ckpt"), {"m": StateDict(x=arr)}
            )
            stall_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            pending.wait()
            drain_s = time.perf_counter() - t0
            fleet_summary = None
            try:
                bus = fleet.get_bus()
                if bus is not None:
                    bus.publish(force=True)
                    view = aggregate.fleet_view(bus.read_beacons())
                    mine = (view.get("per_rank") or {}).get(0) or {}
                    fleet_summary = {
                        "ranks": view.get("ranks"),
                        "engine": mine.get("engine"),
                        "budget_hwm": mine.get("budget_hwm"),
                        "phase": mine.get("phase"),
                        "anomalies": mine.get("anomalies"),
                    }
            except Exception as e:  # fail-soft by design
                fleet_summary = {"error": repr(e)}
        fleet.reset()  # back to the ambient knob state
        ds = pending.drain_stats
        rec = {
            "devices": n_devices,
            "payload_gb": round(payload_gb, 4),
            "stall_s": round(stall_s, 4),
            "drain_s": round(drain_s, 4),
            "drain_gbps": round(payload_gb / max(drain_s, 1e-9), 4),
            "stage_busy_s": round(ds.get("stage_busy_s", 0.0), 3),
            "io_busy_s": round(ds.get("io_busy_s", 0.0), 3),
            "overlap_s": round(ds.get("overlap_s", 0.0), 3),
            "fleet": fleet_summary,
        }
        with open(out_path, "w") as f:
            json.dump(rec, f)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_cell(n_devices: int, total_mb: float) -> dict:
    out_path = tempfile.mktemp(suffix=".json", prefix="tss_multichip_cell_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    # Force the virtual device count on CPU hosts; appended last so it wins
    # over any pre-set flag (last duplicate wins in XLA).
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.abspath(__file__),
                "--child",
                str(n_devices),
                str(total_mb),
                out_path,
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=900,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"cell N={n_devices} failed:\n{proc.stderr[-2000:]}"
            )
        with open(out_path) as f:
            return json.load(f)
    finally:
        if os.path.exists(out_path):
            os.remove(out_path)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(int(sys.argv[2]), float(sys.argv[3]), sys.argv[4])
        return
    total_mb = float(os.environ.get("MULTICHIP_BENCH_MB", "256"))
    device_counts = [
        int(n)
        for n in os.environ.get("MULTICHIP_BENCH_DEVICES", "1,2,4,8").split(
            ","
        )
        if n.strip()
    ]
    curve = []
    for n in device_counts:
        rec = run_cell(n, total_mb)
        curve.append(rec)
        log(f"N={n}: {rec}")
    best = max(curve, key=lambda r: r["drain_gbps"])
    base = curve[0]
    print(
        json.dumps(
            {
                "metric": "drain_gbps_at_max_devices",
                "value": curve[-1]["drain_gbps"],
                "unit": "GB/s",
                "detail": {
                    "payload_mb": total_mb,
                    "curve": curve,
                    "scaling_vs_single": round(
                        curve[-1]["drain_gbps"]
                        / max(base["drain_gbps"], 1e-9),
                        3,
                    ),
                    "best": {
                        "devices": best["devices"],
                        "drain_gbps": best["drain_gbps"],
                    },
                },
            }
        )
    )


if __name__ == "__main__":
    main()
