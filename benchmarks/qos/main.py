"""QoS preemption benchmark: foreground-restore latency under a
concurrent background drain, priority-aware engine vs FIFO.

The production scenario the engine's priority classes exist for: a serving
replica must restore (FOREGROUND) while the same process is still draining
a background checkpoint (BACKGROUND), with scrub / gc / cache-populate
traffic riding the same machinery at background priority. Before the
engine, all of that competed FIFO for the process's storage bandwidth;
with QoS on, the drain yields its next admission (budget, io-pool slots,
stream chunks) to the restore at chunk granularity and resumes the moment
the restore's demand clears.

Two legs:

**Engine leg (the headline)** — drives the engine APIs directly
(``execute_write_reqs`` at BACKGROUND on a drain thread,
``execute_read_reqs`` at FOREGROUND on the main thread — the exact
production thread shape) against one shared-bandwidth disk model: a
process-wide token bucket (``QOS_BENCH_DISK_MBPS``) that every byte either
operation moves must draw from, the standard way to make "one disk, two
operations" deterministic on CI hosts whose real disk is too fast and too
noisy to couple the two ops. Interleaved A/B (alternating order): the ON
side runs with the arbiter enabled, the OFF side with
``TORCHSNAPSHOT_TPU_QOS=0`` — same schedule, FIFO. Reported: foreground
read-op p50/p99 per side, the OFF/ON p99 ratio (>1 = priorities beat
FIFO), drain walls per side (the cost: a bounded drain slowdown buys the
foreground latency), and the drain engine's preemption counters.

**End-to-end leg (fail-soft smoke)** — the same scenario through the
public API on the real disk: ``async_take(qos="background")`` +
``restore(qos="foreground")`` racing in one process; asserts both complete
(drain verifies clean, restores bit-exact) and records whatever overlap /
preemption the host's timing produced.

  python benchmarks/qos/main.py                    # acceptance scale
  QOS_BENCH_BG_MB=8 QOS_BENCH_FG_MB=1 ... main.py  # smoke scale (tier-1)

Env knobs: QOS_BENCH_BG_MB (default 64), QOS_BENCH_FG_MB (8),
QOS_BENCH_RESTORES (3), QOS_BENCH_REPS (3), QOS_BENCH_DISK_MBPS (200),
QOS_BENCH_OBJ_MB (4). The last JSON line on stdout is the
machine-readable result.
"""

import asyncio
import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

BG_MB = int(os.environ.get("QOS_BENCH_BG_MB", "64"))
FG_MB = int(os.environ.get("QOS_BENCH_FG_MB", "8"))
RESTORES = int(os.environ.get("QOS_BENCH_RESTORES", "3"))
REPS = int(os.environ.get("QOS_BENCH_REPS", "3"))
DISK_MBPS = float(os.environ.get("QOS_BENCH_DISK_MBPS", "200"))
OBJ_MB = int(os.environ.get("QOS_BENCH_OBJ_MB", "2"))


def log(msg: str) -> None:
    print(msg, flush=True)


class TokenBucket:
    """One shared-bandwidth disk: every byte any operation moves draws a
    token. Thread-safe (the drain thread's loop and the main loop both
    consume); refills continuously at ``rate_bytes_s``, capacity one
    object's worth so neither side can bank a burst."""

    def __init__(self, rate_bytes_s: float, cap_bytes: int) -> None:
        self.rate = rate_bytes_s
        self.cap = cap_bytes
        self._tokens = float(cap_bytes)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self) -> None:
        now = time.monotonic()
        self._tokens = min(
            self.cap, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    async def consume(self, nbytes: int) -> None:
        while True:
            with self._lock:
                self._refill()
                if self._tokens >= nbytes:
                    self._tokens -= nbytes
                    return
                missing = nbytes - self._tokens
            await asyncio.sleep(min(0.05, missing / self.rate))


class SharedDiskPlugin:
    """A memory-backed StoragePlugin whose reads and writes draw from one
    shared token bucket — the two-operations-one-disk model."""

    supports_streaming = False

    def __init__(self, bucket: TokenBucket, objects=None) -> None:
        self.bucket = bucket
        self.objects = objects if objects is not None else {}

    async def write(self, write_io) -> None:
        data = bytes(memoryview(write_io.buf))
        await self.bucket.consume(len(data))
        self.objects[write_io.path] = data

    async def read(self, read_io) -> None:
        data = self.objects[read_io.path]
        if read_io.byte_range is not None:
            begin, end = read_io.byte_range
            data = data[begin:end]
        await self.bucket.consume(len(data))
        read_io.buf.write(data)

    async def delete(self, path: str) -> None:
        self.objects.pop(path, None)

    async def close(self) -> None:
        pass


class _BytesStager:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.stream_holds_full_buffer = False
        self.defer_staging = False

    async def stage_buffer(self, executor=None):
        return self.data

    def get_staging_cost_bytes(self) -> int:
        return len(self.data)

    def can_stream(self) -> bool:
        return False


class _NullConsumer:
    def __init__(self, nbytes: int) -> None:
        self.nbytes = nbytes

    async def consume_buffer(self, buf, executor=None) -> None:
        assert memoryview(buf).nbytes == self.nbytes

    def get_consuming_cost_bytes(self) -> int:
        return self.nbytes


def engine_side(qos_on: bool, rep: int) -> dict:
    from torchsnapshot_tpu.engine import Priority
    from torchsnapshot_tpu.io_types import ReadReq, WriteReq
    from torchsnapshot_tpu.scheduler import (
        execute_read_reqs,
        execute_write_reqs,
    )
    from torchsnapshot_tpu.utils import knobs

    obj = OBJ_MB * 1024 * 1024
    bucket = TokenBucket(DISK_MBPS * 1e6, cap_bytes=obj)
    disk = SharedDiskPlugin(bucket)
    # Foreground payload pre-seeded on the "disk" (drawing no tokens).
    fg_chunks = max(1, FG_MB // OBJ_MB)
    rng = np.random.default_rng(100 + rep)
    for i in range(fg_chunks):
        disk.objects[f"fg/{i}"] = rng.integers(
            0, 256, size=obj, dtype=np.uint8
        ).tobytes()
    bg_payload = bytes(obj)
    n_bg = max(1, BG_MB // OBJ_MB)

    drain_record = {}
    drain_ready = threading.Event()
    restores_done = threading.Event()

    def drain_thread() -> None:
        async def drain() -> None:
            # defer_staging: the async-take shape — capture returns
            # immediately and the WHOLE drain runs in complete(), where
            # the foreground restores race it.
            reqs = [
                WriteReq(
                    f"bg/{i}", _BytesStager(bg_payload), defer_staging=True
                )
                for i in range(n_bg)
            ]
            t0 = time.perf_counter()
            # A bounded budget (a few objects' worth) keeps admission
            # CONTINUOUS through the drain — the production shape, where
            # the budget is a fraction of the checkpoint — so the engine
            # has admissions left to yield when foreground demand arrives.
            pending = await execute_write_reqs(
                reqs,
                disk,
                memory_budget_bytes=4 * obj,
                rank=0,
                priority=Priority.BACKGROUND,
            )
            drain_ready.set()
            await pending.complete()
            drain_record["wall_s"] = round(time.perf_counter() - t0, 3)
            eng = pending._pipeline._engine
            drain_record["preemptions"] = eng.preemptions
            drain_record["preempted_wait_s"] = round(eng.preempted_wait_s, 3)

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(drain())
        finally:
            loop.close()
            restores_done.wait(timeout=60)

    walls = []

    def restore_once() -> float:
        async def go() -> None:
            reqs = [
                ReadReq(f"fg/{i}", _NullConsumer(obj))
                for i in range(fg_chunks)
            ]
            await execute_read_reqs(
                reqs,
                disk,
                memory_budget_bytes=64 * 1024 * 1024,
                rank=0,
                priority=Priority.FOREGROUND,
            )

        loop = asyncio.new_event_loop()
        t0 = time.perf_counter()
        try:
            loop.run_until_complete(go())
        finally:
            loop.close()
        return time.perf_counter() - t0

    # Queue depth 4: the disk model's in-flight op cap (shared by both
    # sides, like a real device queue).
    with knobs.override_qos(qos_on), knobs.override_qos_poll_s(
        0.005
    ), knobs.override_max_concurrent_io(4):
        t = threading.Thread(target=drain_thread)
        t.start()
        drain_ready.wait(timeout=60)
        try:
            for _k in range(RESTORES):
                walls.append(restore_once())
        finally:
            restores_done.set()
        t.join(timeout=120)
    rec = {
        "restore_walls_s": [round(w, 4) for w in walls],
        "drain": dict(drain_record),
    }
    log(f"engine rep {rep} [{'on' if qos_on else 'off'}]: {rec}")
    return rec


def _fleet_summary() -> dict:
    """Read back this process's own beacon and compress it to the fields
    the artifact keeps (fail-soft: absent beats a sunk benchmark)."""
    from torchsnapshot_tpu.telemetry import aggregate, fleet

    bus = fleet.get_bus()
    if bus is None:
        return {"enabled": False}
    bus.publish(force=True)
    view = aggregate.fleet_view(bus.read_beacons())
    per_rank = view.get("per_rank") or {}
    return {
        "enabled": True,
        "ranks": view.get("ranks"),
        "world_size": view.get("world_size"),
        "edges": view.get("edges"),
        "per_rank": {
            str(r): {
                k: b.get(k)
                for k in (
                    "op",
                    "phase",
                    "engine",
                    "engine_paused",
                    "budget_hwm",
                    "qos_demand",
                    "anomalies",
                )
            }
            for r, b in per_rank.items()
        },
    }


def _p99(samples):
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(round(0.99 * (len(ordered) - 1))))
    return ordered[idx]


def e2e_leg(root: str) -> dict:
    """Fail-soft end-to-end smoke through the public API on the real disk:
    both ops complete, restores bit-exact, drain verifies clean; overlap /
    preemption counters recorded for whatever this host's timing produced."""
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.telemetry import fleet
    from torchsnapshot_tpu.utils import knobs

    rng = np.random.default_rng(7)
    fg_state = StateDict(
        v=rng.standard_normal(FG_MB * 1024 * 256).astype(np.float32)
    )
    fg_path = os.path.join(root, "fg")
    Snapshot.take(fg_path, {"m": fg_state})
    bg_state = StateDict(
        **{
            f"w{i}": rng.standard_normal(1024 * 256).astype(np.float32)
            for i in range(max(2, BG_MB))
        }
    )
    # Fleet telemetry forced on (world=1 over the in-process store, so
    # "auto" resolves off): the artifact embeds the fleet view so the QoS
    # rollup carries the same beacon rollup operators see live.
    fleet_summary = None
    with knobs.override_qos_poll_s(0.005), knobs.override_stream_chunk_bytes(
        1024 * 1024
    ), knobs.override_fleet_telemetry("1"), knobs.override_fleet_beacon_s(
        0.05
    ):
        fleet.reset()
        pending = Snapshot.async_take(
            os.path.join(root, "bg"), {"m": bg_state}, qos="background"
        )
        overlapped = 0
        walls = []
        for _k in range(RESTORES):
            restored = StateDict(v=np.zeros_like(fg_state["v"]))
            overlapped += 0 if pending.done() else 1
            t0 = time.perf_counter()
            Snapshot(fg_path).restore({"m": restored}, qos="foreground")
            walls.append(round(time.perf_counter() - t0, 4))
            assert np.array_equal(restored["v"], fg_state["v"])
        try:
            fleet_summary = _fleet_summary()
        except Exception as e:  # fail-soft by design
            fleet_summary = {"enabled": True, "error": repr(e)}
        pending.wait()
    fleet.reset()  # back to the ambient knob state
    eng = pending._pending_io_work._pipeline._engine
    assert Snapshot(os.path.join(root, "bg")).verify() == {}
    return {
        "restore_walls_s": walls,
        "restores_overlapping_drain": overlapped,
        "drain_preemptions": eng.preemptions,
        "drain_preempted_wait_s": round(eng.preempted_wait_s, 3),
        "fleet": fleet_summary,
    }


def main() -> None:
    root = tempfile.mkdtemp(prefix="qos_bench_")
    try:
        sides = {"on": [], "off": []}
        for rep in range(REPS):
            order = (True, False) if rep % 2 == 0 else (False, True)
            for enabled in order:
                sides["on" if enabled else "off"].append(
                    engine_side(enabled, rep)
                )

        def walls(label):
            return [w for r in sides[label] for w in r["restore_walls_s"]]

        on_walls, off_walls = walls("on"), walls("off")
        on_p99, off_p99 = _p99(on_walls), _p99(off_walls)
        preemptions_on = sum(
            r["drain"].get("preemptions", 0) for r in sides["on"]
        )
        # Mechanics gates (deterministic under the shared-disk model): the
        # QoS-on drain yielded to the foreground reads; the FIFO side never
        # did; and the foreground p99 improved.
        assert preemptions_on > 0, "QoS-on drain recorded no preemptions"
        assert (
            sum(r["drain"].get("preemptions", 0) for r in sides["off"]) == 0
        ), "FIFO side must record no preemptions"

        e2e = e2e_leg(root)
        log(f"e2e leg: {e2e}")

        result = {
            "metric": "qos_fg_restore_p99_speedup_vs_fifo",
            "value": round(off_p99 / max(on_p99, 1e-9), 3),
            "unit": "x",
            "detail": {
                "bg_mb": BG_MB,
                "fg_mb": FG_MB,
                "disk_mbps_model": DISK_MBPS,
                "reps": REPS,
                "restores_per_drain": RESTORES,
                "fg_restore_p50_s": {
                    "on": round(statistics.median(on_walls), 4),
                    "off": round(statistics.median(off_walls), 4),
                },
                "fg_restore_p99_s": {
                    "on": round(on_p99, 4),
                    "off": round(off_p99, 4),
                },
                "drain_wall_s": {
                    "on": round(
                        statistics.median(
                            r["drain"]["wall_s"] for r in sides["on"]
                        ),
                        3,
                    ),
                    "off": round(
                        statistics.median(
                            r["drain"]["wall_s"] for r in sides["off"]
                        ),
                        3,
                    ),
                },
                "drain_preemptions_on": preemptions_on,
                "drain_preempted_wait_s_on": round(
                    sum(
                        r["drain"].get("preempted_wait_s", 0.0)
                        for r in sides["on"]
                    ),
                    3,
                ),
                "sides": sides,
                "e2e": e2e,
            },
        }
        log(
            f"foreground restore p99: on={on_p99:.4f}s off={off_p99:.4f}s "
            f"({result['value']}x)"
        )
        if result["value"] <= 1.0:
            # Fail-soft, loud: the artifact still records the round, but a
            # priority-on p99 that does NOT beat FIFO is the regression
            # this harness exists to catch.
            result["qos_inverted"] = True
            log(
                "WARNING: qos bench: priority-on foreground p99 "
                f"({on_p99:.4f}s) did not beat FIFO ({off_p99:.4f}s) — "
                "preemption is not delivering foreground latency"
            )
        print(json.dumps(result))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
