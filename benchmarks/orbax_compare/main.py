"""Head-to-head vs orbax.checkpoint — the incumbent JAX/TPU checkpointer.

The reference's flagship table compares against ``torch.save``
(``benchmarks/ddp/README.md``); the equivalent incumbent on TPU is orbax.
This harness saves/restores the SAME state with both libraries on the same
devices and reports, per leg:

- async save **stall** (time until the save call returns and training may
  resume) — the headline metric;
- total save wall time (stall + background drain / wait_until_finished);
- blocking restore time, with bit-exactness asserted for both.

Legs (``--leg``, VERDICT round 2 item 5 — the differentiating axes):

- ``single``  — one-chip bf16 param pytree (the round-2 leg);
- ``sharded`` — params + adam moments sharded over a (dp, tp) device mesh;
- ``reshard`` — saved under one PartitionSpec layout, restored into a
  transposed layout (both libraries reshard on restore);
- ``incremental`` — LoRA-shaped state (frozen backbone + small adapter):
  this library's ``take(base=prev)`` hard-link dedup vs orbax's full save
  of the same changed state.

  python benchmarks/orbax_compare/main.py --gb 0.5
  python benchmarks/orbax_compare/main.py --cpu --leg sharded

Runs on the real TPU chip by default; pass --cpu for the virtual 8-device
mesh (required for the sharded/reshard legs on a single-chip host).
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def _bit_eq(a, b) -> bool:
    import numpy as np

    return (
        np.ascontiguousarray(np.asarray(a)).view(np.uint8).tobytes()
        == np.ascontiguousarray(np.asarray(b)).view(np.uint8).tobytes()
    )


def _report(leg: str, tss, orbax) -> None:
    print(f"--- leg: {leg}")
    print(f"{'':24s}{'stall_s':>10s}{'total_s':>10s}{'restore_s':>10s}")
    print(f"{'torchsnapshot_tpu':24s}{tss[0]:>10.3f}{tss[1]:>10.2f}{tss[2]:>10.2f}")
    print(f"{'orbax':24s}{orbax[0]:>10.3f}{orbax[1]:>10.2f}{orbax[2]:>10.2f}")
    print(
        f"stall speedup vs orbax: {orbax[0] / max(tss[0], 1e-9):.1f}x; "
        f"total {orbax[1] / max(tss[1], 1e-9):.2f}x; "
        f"restore {orbax[2] / max(tss[2], 1e-9):.2f}x"
    )


def _run_sharded_leg(root: str, gb: float, reshard: bool, reps: int = 2) -> None:
    """Params + adam moments on a (dp, tp) mesh; optionally restore into a
    TRANSPOSED layout (elasticity/resharding — the axis this library claims
    as its differentiation; orbax reshards via abstract targets)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import orbax.checkpoint as ocp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict

    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(ndev // 2, 2), ("dp", "tp"))
    d = 2048
    n_layers = max(1, round(gb * 1e9 / (4 * d * d * (2 + 4 + 4))))

    def build(seed: int):
        key = jax.random.PRNGKey(seed)
        spec = NamedSharding(mesh, P("dp", "tp"))
        state = {}
        for i in range(n_layers):
            key, k1 = jax.random.split(key)
            w = jax.device_put(
                jax.random.normal(k1, (d, 4 * d), jnp.bfloat16), spec
            )
            state[f"layer_{i}"] = {
                "w": w,
                "mu": jax.device_put(jnp.zeros((d, 4 * d), jnp.float32), spec),
                "nu": jax.device_put(jnp.ones((d, 4 * d), jnp.float32), spec),
            }
        jax.block_until_ready(state)
        return state

    def target_sharding():
        # Transposed axis order + different spec for the reshard leg.
        tmesh = Mesh(np.array(jax.devices()).reshape(2, ndev // 2), ("tp", "dp"))
        return NamedSharding(tmesh, P(None, "tp")) if reshard else NamedSharding(
            mesh, P("dp", "tp")
        )

    warm = build(100)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(warm))
    print(f"sharded state: {nbytes/1e9:.2f} GB over {ndev} devices", file=sys.stderr)

    def run_tss(state, tag):
        app = {"m": StateDict(**state)}
        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(root, f"tss{tag}"), app)
        stall = time.perf_counter() - t0
        pending.wait()
        total = time.perf_counter() - t0
        tspec = target_sharding()
        tgt = StateDict(
            **{
                k: {
                    kk: jax.device_put(jnp.zeros_like(vv), tspec)
                    for kk, vv in v.items()
                }
                for k, v in state.items()
            }
        )
        t0 = time.perf_counter()
        Snapshot(os.path.join(root, f"tss{tag}")).restore({"m": tgt})
        restore_s = time.perf_counter() - t0
        for k, v in state.items():
            for kk in v:
                assert _bit_eq(tgt[k][kk], v[kk]), (k, kk)
        return stall, total, restore_s

    def run_orbax(state, tag):
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        path = os.path.join(root, f"orbax{tag}")
        t0 = time.perf_counter()
        ckptr.save(path, args=ocp.args.StandardSave(state))
        stall = time.perf_counter() - t0
        ckptr.wait_until_finished()
        total = time.perf_counter() - t0
        tspec = target_sharding()
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=tspec),
            state,
        )
        restorer = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        t0 = time.perf_counter()
        restored = restorer.restore(path, args=ocp.args.StandardRestore(abstract))
        restore_s = time.perf_counter() - t0
        for k, v in state.items():
            for kk in v:
                assert _bit_eq(restored[k][kk], v[kk]), (k, kk)
        ckptr.close()
        restorer.close()
        return stall, total, restore_s

    # Warmups (jit of defensive copies / tensorstore spinup), then
    # INTERLEAVED reps on fresh states with MEDIAN reporting (+ per-rep
    # lines and restore spreads): this host's page-cache writeback makes
    # any single IO-heavy measurement noisy at the 2x level, and serial
    # one-shot runs hand one library the bad window (same posture as
    # bench.py's A/B medians).
    Snapshot.async_take(os.path.join(root, "tss_warm"), {"m": StateDict(**warm)}).wait()
    _w = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    _w.save(os.path.join(root, "orbax_warm"), args=ocp.args.StandardSave(warm))
    _w.wait_until_finished()
    _w.close()
    tss_runs = []
    orbax_runs = []
    for rep in range(reps):
        # Alternate which library goes first so page-cache/writeback drift
        # biases neither side.
        if rep % 2 == 0:
            tss_runs.append(run_tss(build(10 + rep), tag=rep))
            orbax_runs.append(run_orbax(build(20 + rep), tag=rep))
        else:
            orbax_runs.append(run_orbax(build(20 + rep), tag=rep))
            tss_runs.append(run_tss(build(10 + rep), tag=rep))
        print(
            f"rep {rep}: tss (stall/total/restore) "
            f"{tss_runs[-1][0]:.3f}/{tss_runs[-1][1]:.2f}/{tss_runs[-1][2]:.2f} s, "
            f"orbax {orbax_runs[-1][0]:.3f}/{orbax_runs[-1][1]:.2f}/{orbax_runs[-1][2]:.2f} s",
            file=sys.stderr,
        )
    import statistics

    med = lambda runs: tuple(  # noqa: E731
        statistics.median(r[i] for r in runs) for i in range(3)
    )
    _report("reshard" if reshard else "sharded", med(tss_runs), med(orbax_runs))
    for name, runs in (("tss", tss_runs), ("orbax", orbax_runs)):
        print(
            f"{name} restore spread: "
            f"{min(r[2] for r in runs):.2f}-{max(r[2] for r in runs):.2f} s "
            f"over {reps} interleaved reps",
        )


def _run_incremental_leg(root: str, gb: float) -> None:
    """LoRA-shaped state: frozen backbone + small adapter that changes per
    step. This library's second take dedups the backbone against the first
    via ``base=`` (hard links); orbax re-saves everything."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import orbax.checkpoint as ocp

    from torchsnapshot_tpu import Snapshot, StateDict

    n_frozen = max(1, round(gb * 1e9 / (16 * 1024 * 1024)))

    def build(seed: int, step: int):
        key = jax.random.PRNGKey(seed)
        state = {}
        for i in range(n_frozen):
            key, k1 = jax.random.split(key)
            state[f"frozen_{i}"] = jax.random.normal(k1, (2048, 2048), jnp.bfloat16)
        key, k2 = jax.random.split(jax.random.PRNGKey(1000 + step))
        state["adapter"] = jax.random.normal(k2, (2048, 128), jnp.float32)
        jax.block_until_ready(state)
        return state

    def run_tss():
        # Pin dedup digests ON for both takes: the auto default turns them
        # off on single-vCPU hosts, and a base without sha256 identities
        # silently degrades the second take to a full rewrite — this leg
        # would then compare orbax against nothing (ADVICE round 5).
        os.environ["TORCHSNAPSHOT_TPU_DEDUP_DIGESTS"] = "1"
        s0 = build(0, step=0)
        p0 = os.path.join(root, "tss_step0")
        t0 = time.perf_counter()
        Snapshot.take(p0, {"m": StateDict(**s0)})
        first_s = time.perf_counter() - t0
        s1 = dict(s0, adapter=build(0, step=1)["adapter"])
        p1 = os.path.join(root, "tss_step1")
        t0 = time.perf_counter()
        Snapshot.take(p1, {"m": StateDict(**s1)}, base=p0)
        incr_s = time.perf_counter() - t0
        # The claimed speedup is only real if the frozen objects were
        # hard-linked, not rewritten; same inode proves it.
        loc = Snapshot(p1).get_manifest()["0/m/frozen_0"].location
        assert os.path.samefile(
            os.path.join(p0, loc), os.path.join(p1, loc)
        ), "frozen object was rewritten, not hard-linked — dedup silently degraded"
        tgt = StateDict(**{k: jnp.zeros_like(v) for k, v in s1.items()})
        t0 = time.perf_counter()
        Snapshot(p1).restore({"m": tgt})
        restore_s = time.perf_counter() - t0
        for k, v in s1.items():
            assert _bit_eq(tgt[k], v), k
        return first_s, incr_s, restore_s

    def run_orbax():
        ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        s0 = build(2, step=0)
        t0 = time.perf_counter()
        ckptr.save(os.path.join(root, "orbax_step0"), args=ocp.args.StandardSave(s0))
        first_s = time.perf_counter() - t0
        s1 = dict(s0, adapter=build(2, step=1)["adapter"])
        t0 = time.perf_counter()
        ckptr.save(os.path.join(root, "orbax_step1"), args=ocp.args.StandardSave(s1))
        second_s = time.perf_counter() - t0
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding), s1
        )
        t0 = time.perf_counter()
        restored = ckptr.restore(
            os.path.join(root, "orbax_step1"), args=ocp.args.StandardRestore(abstract)
        )
        restore_s = time.perf_counter() - t0
        for k, v in s1.items():
            assert _bit_eq(restored[k], v), k
        ckptr.close()
        return first_s, second_s, restore_s

    tss = run_tss()
    orbax = run_orbax()
    print("--- leg: incremental (LoRA-shaped; 2nd save after adapter-only change)")
    print(f"{'':24s}{'first_save_s':>14s}{'second_save_s':>14s}{'restore_s':>10s}")
    print(f"{'torchsnapshot_tpu':24s}{tss[0]:>14.2f}{tss[1]:>14.2f}{tss[2]:>10.2f}")
    print(f"{'orbax (full saves)':24s}{orbax[0]:>14.2f}{orbax[1]:>14.2f}{orbax[2]:>10.2f}")
    print(
        f"second-save speedup vs orbax: {orbax[1] / max(tss[1], 1e-9):.1f}x "
        f"(take(base=prev) rewrites only the changed adapter)"
    )


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=0.5)
    parser.add_argument(
        "--reps", type=int, default=2, help="interleaved reps per library (sharded legs)"
    )
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument(
        "--leg",
        choices=["single", "sharded", "reshard", "incremental", "all"],
        default="single",
    )
    args = parser.parse_args()

    if args.cpu:
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    print(f"device: {jax.devices()[0].device_kind}", file=sys.stderr)

    if args.leg in ("sharded", "reshard", "incremental", "all"):
        root = tempfile.mkdtemp()
        try:
            if args.leg in ("sharded", "all"):
                _run_sharded_leg(
                    os.path.join(root, "sh"), args.gb, reshard=False, reps=args.reps
                )
            if args.leg in ("reshard", "all"):
                _run_sharded_leg(
                    os.path.join(root, "rs"), args.gb, reshard=True, reps=args.reps
                )
            if args.leg in ("incremental", "all"):
                _run_incremental_leg(os.path.join(root, "inc"), args.gb)
        finally:
            shutil.rmtree(root, ignore_errors=True)
        if args.leg != "all":
            return
        # fall through to the single leg for --leg all

    d_model = 4096
    n_layers = max(1, round(args.gb * 1e9 / (4 * d_model * d_model * 2)))

    @jax.jit
    def mk(key):
        return jax.random.normal(key, (d_model, 4 * d_model), jnp.bfloat16)

    def build(seed: int):
        key = jax.random.PRNGKey(seed)
        params = {}
        for i in range(n_layers):
            key, sub = jax.random.split(key)
            params[f"layer_{i}"] = mk(sub)
        jax.block_until_ready(params)
        return params

    # FAIRNESS: each library gets its own freshly generated params for the
    # timed run, never host-transferred beforehand. jax Arrays cache their
    # host copy after the first device->host transfer, so re-saving the
    # same (or warmed-up) arrays lets a capture-to-host design report a
    # near-zero "stall" that no training run would ever see — every real
    # checkpoint saves arrays whose values changed since the last transfer.
    warm_params = build(100)
    params_tss = build(0)
    params_orbax = build(1)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(params_tss))
    print(f"state: {nbytes/1e9:.2f} GB bf16", file=sys.stderr)

    root = tempfile.mkdtemp()

    def run_tss():
        # Warmup take (jit of defensive copies, pools) on separate data.
        Snapshot.async_take(
            os.path.join(root, "tss_warm"), {"m": StateDict(**warm_params)}
        ).wait()
        params = params_tss
        app = {"m": StateDict(**params)}
        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(root, "tss"), app)
        stall = time.perf_counter() - t0
        pending.wait()
        total = time.perf_counter() - t0
        tgt = StateDict(**{k: jnp.zeros_like(v) for k, v in params.items()})
        t0 = time.perf_counter()
        Snapshot(os.path.join(root, "tss")).restore({"m": tgt})
        restore_s = time.perf_counter() - t0
        for k in params:
            assert (
                np.ascontiguousarray(np.asarray(tgt[k])).view(np.uint8).tobytes()
                == np.ascontiguousarray(np.asarray(params[k])).view(np.uint8).tobytes()
            ), f"torchsnapshot_tpu restore mismatch at {k}"
        return stall, total, restore_s

    def run_orbax():
        import orbax.checkpoint as ocp

        path = os.path.join(root, "orbax")
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # Warmup on a throwaway path with separate data (see FAIRNESS note).
        warm = os.path.join(root, "orbax_warm")
        ckptr.save(warm, args=ocp.args.StandardSave(warm_params))
        ckptr.wait_until_finished()
        params = params_orbax
        t0 = time.perf_counter()
        ckptr.save(path, args=ocp.args.StandardSave(params))
        stall = time.perf_counter() - t0
        ckptr.wait_until_finished()
        total = time.perf_counter() - t0
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            params,
        )
        restorer = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        t0 = time.perf_counter()
        restored = restorer.restore(path, args=ocp.args.StandardRestore(abstract))
        restore_s = time.perf_counter() - t0
        for k in params:
            assert (
                np.ascontiguousarray(np.asarray(restored[k])).view(np.uint8).tobytes()
                == np.ascontiguousarray(np.asarray(params[k])).view(np.uint8).tobytes()
            ), f"orbax restore mismatch at {k}"
        ckptr.close()
        restorer.close()
        return stall, total, restore_s

    tss = run_tss()
    orbax = run_orbax()
    shutil.rmtree(root, ignore_errors=True)
    _report("single", tss, orbax)


if __name__ == "__main__":
    main()
