"""Head-to-head vs orbax.checkpoint — the incumbent JAX/TPU checkpointer.

The reference's flagship table compares against ``torch.save``
(``benchmarks/ddp/README.md``); the equivalent incumbent on TPU is orbax.
This harness saves/restores the SAME bf16 param pytree with both libraries
on the same device and reports:

- async save **stall** (time until the save call returns and training may
  resume) — the headline metric;
- total save wall time (stall + background drain / wait_until_finished);
- blocking restore time, with bit-exactness asserted for both.

  python benchmarks/orbax_compare/main.py --gb 0.5

Run on the real TPU chip by default; pass --cpu for the virtual-device mesh.
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=0.5)
    parser.add_argument("--cpu", action="store_true")
    args = parser.parse_args()

    if args.cpu:
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    print(f"device: {jax.devices()[0].device_kind}", file=sys.stderr)

    d_model = 4096
    n_layers = max(1, round(args.gb * 1e9 / (4 * d_model * d_model * 2)))

    @jax.jit
    def mk(key):
        return jax.random.normal(key, (d_model, 4 * d_model), jnp.bfloat16)

    def build(seed: int):
        key = jax.random.PRNGKey(seed)
        params = {}
        for i in range(n_layers):
            key, sub = jax.random.split(key)
            params[f"layer_{i}"] = mk(sub)
        jax.block_until_ready(params)
        return params

    # FAIRNESS: each library gets its own freshly generated params for the
    # timed run, never host-transferred beforehand. jax Arrays cache their
    # host copy after the first device->host transfer, so re-saving the
    # same (or warmed-up) arrays lets a capture-to-host design report a
    # near-zero "stall" that no training run would ever see — every real
    # checkpoint saves arrays whose values changed since the last transfer.
    warm_params = build(100)
    params_tss = build(0)
    params_orbax = build(1)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(params_tss))
    print(f"state: {nbytes/1e9:.2f} GB bf16", file=sys.stderr)

    root = tempfile.mkdtemp()

    def run_tss():
        # Warmup take (jit of defensive copies, pools) on separate data.
        Snapshot.async_take(
            os.path.join(root, "tss_warm"), {"m": StateDict(**warm_params)}
        ).wait()
        params = params_tss
        app = {"m": StateDict(**params)}
        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(root, "tss"), app)
        stall = time.perf_counter() - t0
        pending.wait()
        total = time.perf_counter() - t0
        tgt = StateDict(**{k: jnp.zeros_like(v) for k, v in params.items()})
        t0 = time.perf_counter()
        Snapshot(os.path.join(root, "tss")).restore({"m": tgt})
        restore_s = time.perf_counter() - t0
        for k in params:
            assert (
                np.ascontiguousarray(np.asarray(tgt[k])).view(np.uint8).tobytes()
                == np.ascontiguousarray(np.asarray(params[k])).view(np.uint8).tobytes()
            ), f"torchsnapshot_tpu restore mismatch at {k}"
        return stall, total, restore_s

    def run_orbax():
        import orbax.checkpoint as ocp

        path = os.path.join(root, "orbax")
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # Warmup on a throwaway path with separate data (see FAIRNESS note).
        warm = os.path.join(root, "orbax_warm")
        ckptr.save(warm, args=ocp.args.StandardSave(warm_params))
        ckptr.wait_until_finished()
        params = params_orbax
        t0 = time.perf_counter()
        ckptr.save(path, args=ocp.args.StandardSave(params))
        stall = time.perf_counter() - t0
        ckptr.wait_until_finished()
        total = time.perf_counter() - t0
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            params,
        )
        restorer = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        t0 = time.perf_counter()
        restored = restorer.restore(path, args=ocp.args.StandardRestore(abstract))
        restore_s = time.perf_counter() - t0
        for k in params:
            assert (
                np.ascontiguousarray(np.asarray(restored[k])).view(np.uint8).tobytes()
                == np.ascontiguousarray(np.asarray(params[k])).view(np.uint8).tobytes()
            ), f"orbax restore mismatch at {k}"
        ckptr.close()
        restorer.close()
        return stall, total, restore_s

    tss = run_tss()
    orbax = run_orbax()
    shutil.rmtree(root, ignore_errors=True)
    print(f"{'':24s}{'stall_s':>10s}{'total_s':>10s}{'restore_s':>10s}")
    print(f"{'torchsnapshot_tpu':24s}{tss[0]:>10.3f}{tss[1]:>10.2f}{tss[2]:>10.2f}")
    print(f"{'orbax':24s}{orbax[0]:>10.3f}{orbax[1]:>10.2f}{orbax[2]:>10.2f}")
    print(
        f"stall speedup vs orbax: {orbax[0] / max(tss[0], 1e-9):.1f}x; "
        f"total {orbax[1] / max(tss[1], 1e-9):.2f}x; "
        f"restore {orbax[2] / max(tss[2], 1e-9):.2f}x"
    )


if __name__ == "__main__":
    main()
