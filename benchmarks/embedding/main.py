"""Row-sharded embedding save + reshard benchmark
(reference ``benchmarks/torchrec/main.py:54-113``: DLRM row-wise sharded
embedding bags, sync vs async save, 4->2/2->4 rank reshard).

TPU equivalent: a large embedding table row-sharded over the device mesh,
saved, then restored under a different mesh factorization.

  python benchmarks/embedding/main.py --rows 1000000 --dim 128
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=1_000_000)
    parser.add_argument("--dim", type=int, default=128)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict

    n = len(jax.devices())
    rows = args.rows - args.rows % n
    mesh_a = Mesh(np.array(jax.devices()), ("shard",))

    table = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (rows, args.dim), jnp.float32),
        NamedSharding(mesh_a, P("shard")),
    )
    jax.block_until_ready(table)
    gb = table.nbytes / 1e9

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "ckpt")
        t0 = time.perf_counter()
        Snapshot.take(path, {"emb": StateDict(table=table)})
        sync_s = time.perf_counter() - t0
        print(f"row-sharded save {gb:.2f} GB over {n} devices: {sync_s:.2f}s "
              f"({gb / sync_s:.2f} GB/s)")

        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(tmp, "ckpt2"), {"emb": StateDict(table=table)})
        stall = time.perf_counter() - t0
        pending.wait()
        print(f"async stall: {stall:.2f}s")

        # Reshard: restore under a different mesh factorization (the 4->2 /
        # 2->4 reshard of the reference, expressed as mesh reshape).
        if n % 2 == 0:
            mesh_b = Mesh(np.array(jax.devices()).reshape(2, n // 2), ("a", "b"))
            tgt = StateDict(
                table=jax.device_put(
                    jnp.zeros((rows, args.dim), jnp.float32),
                    NamedSharding(mesh_b, P(("a", "b"))),
                )
            )
            t0 = time.perf_counter()
            Snapshot(path).restore({"emb": tgt})
            print(f"reshard restore: {time.perf_counter() - t0:.2f}s")
            ok = np.array_equal(np.asarray(tgt["table"]), np.asarray(table))
            print(f"bit-exact: {ok}")


if __name__ == "__main__":
    main()
