"""Full train-state (params + fp32 optimizer moments) checkpoint benchmark
(reference ``benchmarks/deepspeed_opt/main.py:27-31``: OPT-30B-shaped model,
ZeRO-3 partitioned optimizer state via the DeepSpeed adapter).

TPU equivalent: an adamw train state — bf16 params plus fp32 first/second
moments (3x the param bytes, the same ratio ZeRO-3 shards) — FSDP-sharded
over the mesh and checkpointed through :class:`PyTreeStateful`, the analogue
of the reference's engine adapter (``tricks/deepspeed.py:30-103``).

  python benchmarks/optimizer/main.py --layers 4 --d-model 1024
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--tp", type=int, default=0, help="0 = auto")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        shard_params,
    )
    from torchsnapshot_tpu.tricks.train_state import Box, PyTreeStateful

    n = len(jax.devices())
    tp = args.tp or (2 if n % 2 == 0 else 1)
    if n % tp != 0:
        raise SystemExit(f"--tp {tp} must divide the device count ({n})")
    mesh = Mesh(np.array(jax.devices()).reshape(n // tp, tp), ("dp", "tp"))
    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=args.d_model,
        n_heads=max(4, args.d_model // 128),
        n_layers=args.layers,
        d_ff=4 * args.d_model,
    )
    _, params = init_params(cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    params = shard_params(params, mesh, fsdp=True)

    # fp32 adamw moments inherit each param's sharding (computation follows
    # data), i.e. the optimizer state is FSDP-partitioned like ZeRO-3's.
    tx = optax.adamw(1e-3)
    opt_state = jax.jit(tx.init)(
        jax.tree.map(lambda x: x.astype(jnp.float32), params)
    )
    state = {"params": params, "opt_state": opt_state, "step": 0}
    jax.block_until_ready((params, opt_state))

    nbytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(state) if hasattr(x, "nbytes")
    )
    gb = nbytes / 1e9
    print(f"{gb:.2f} GB train state (params + fp32 moments) on mesh {dict(mesh.shape)}")

    holder = Box(state)
    app_state = {"train_state": PyTreeStateful(holder)}
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "ckpt")
        t0 = time.perf_counter()
        Snapshot.take(path, app_state)
        sync_s = time.perf_counter() - t0
        print(f"sync take: {sync_s:.2f}s ({gb / sync_s:.2f} GB/s)")

        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(tmp, "ckpt2"), app_state)
        stall_s = time.perf_counter() - t0
        pending.wait()
        print(f"async stall: {stall_s:.2f}s")

        zeroed = Box(
            jax.tree.map(
                lambda x: jnp.zeros_like(x) if hasattr(x, "dtype") else x, state
            )
        )
        t0 = time.perf_counter()
        Snapshot(path).restore({"train_state": PyTreeStateful(zeroed)})
        load_s = time.perf_counter() - t0
        print(f"restore: {load_s:.2f}s ({gb / load_s:.2f} GB/s)")

        ok = all(
            np.array_equal(
                np.ascontiguousarray(np.asarray(a)).reshape(-1).view(np.uint8),
                np.ascontiguousarray(np.asarray(b)).reshape(-1).view(np.uint8),
            )
            for a, b in zip(
                (x for x in jax.tree_util.tree_leaves(state) if hasattr(x, "dtype")),
                (
                    x
                    for x in jax.tree_util.tree_leaves(zeroed.value)
                    if hasattr(x, "dtype")
                ),
            )
        )
        print(f"bit-exact: {ok}")


if __name__ == "__main__":
    main()
