"""Staging-only micro-bench: _WritePipeline overhead without a device.

The r02→r05 drain regression (32s → 55s on the same 1.11 GB workload) hid
inside ``stage_busy`` — a single opaque number polluted by TPU/link variance.
This harness makes staging overhead measurable in isolation, bisect-style:

- **synthetic host buffers** (numpy, no device, no D2H variance): a
  ``np.asarray`` on a host array is free, so the measured wall is purely the
  pipeline's own machinery — serialization, hashing, chunk plumbing, budget
  accounting, event-loop dispatch;
- **a null storage sink** (appends/writes discard after a length probe): no
  disk, no page cache, no O_DIRECT alignment — ``io_busy`` collapses to the
  call overhead, so ``stage_busy`` is the whole story;
- **an ablation matrix** over the staging features that have historically
  eaten drain time: streaming on/off, checksums on/off, dedup digests
  on/off. A regression bisects by diffing configs between two commits.

Reported per config: wall seconds, GB/s through staging, and the
``stage_d2h_s``/``stage_serialize_s``/``stage_hash_s`` decomposition. One
JSON line on stdout; progress on stderr.

  python benchmarks/staging/main.py                 # default ~0.5 GB
  STAGING_BENCH_MB=64 python benchmarks/staging/main.py   # quick smoke
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

from torchsnapshot_tpu.io_preparers.array import ArrayIOPreparer  # noqa: E402
from torchsnapshot_tpu.io_types import (  # noqa: E402
    ReadIO,
    StoragePlugin,
    StorageWriteStream,
    WriteIO,
)
from torchsnapshot_tpu.scheduler import execute_write_reqs  # noqa: E402
from torchsnapshot_tpu.utils import knobs  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class _NullWriteStream(StorageWriteStream):
    def __init__(self, plugin: "NullStoragePlugin") -> None:
        self._plugin = plugin

    async def append(self, buf) -> None:
        self._plugin.bytes_sunk += memoryview(buf).nbytes

    async def commit(self) -> None:
        pass

    async def abort(self) -> None:
        pass


class NullStoragePlugin(StoragePlugin):
    """Discards every byte after a length probe: the staging stream runs
    against a zero-cost drain, so the pipeline's wall time IS staging."""

    supports_streaming = True

    def __init__(self) -> None:
        self.bytes_sunk = 0

    async def write(self, write_io: WriteIO) -> None:
        self.bytes_sunk += memoryview(write_io.buf).nbytes

    async def write_stream(self, path: str) -> StorageWriteStream:
        return _NullWriteStream(self)

    async def read(self, read_io: ReadIO) -> None:
        raise FileNotFoundError(read_io.path)

    async def delete(self, path: str) -> None:
        pass  # idempotent: nothing is ever stored


def build_host_state(total_mb: int, arrays: int, seed: int = 0):
    """``arrays`` float32 host arrays summing to ~total_mb MB."""
    rng = np.random.default_rng(seed)
    per = max(1, total_mb // arrays)
    rows = max(2, per * 1024 * 1024 // (1024 * 4))
    return [
        rng.standard_normal((rows, 1024)).astype(np.float32)
        for _ in range(arrays)
    ]


def run_config(
    arrs,
    stream: bool,
    checksums: bool,
    dedup: bool,
    hash_grain: int = None,
    hash_workers: int = None,
) -> dict:
    storage = NullStoragePlugin()
    reqs = []
    for i, a in enumerate(arrs):
        _entry, sub = ArrayIOPreparer.prepare_write(f"obj_{i}", a)
        reqs.extend(sub)
    total = sum(a.nbytes for a in arrs)

    async def go():
        pending = await execute_write_reqs(
            reqs, storage, memory_budget_bytes=2**33, rank=0
        )
        await pending.complete()
        return pending

    import contextlib

    overrides = contextlib.ExitStack()
    if hash_grain is not None:
        overrides.enter_context(knobs.override_hash_chunk_bytes(hash_grain))
    if hash_workers is not None:
        overrides.enter_context(knobs.override_hash_workers(hash_workers))
    loop = asyncio.new_event_loop()
    try:
        with overrides, \
                knobs.override_stream_writes(stream), \
                knobs.override_checksums(checksums), \
                knobs.override_dedup_digests(dedup):
            t0 = time.perf_counter()
            pending = loop.run_until_complete(go())
            wall = time.perf_counter() - t0
    finally:
        loop.close()
    assert storage.bytes_sunk >= total, (storage.bytes_sunk, total)
    stats = pending.pipeline_stats
    return {
        "wall_s": round(wall, 4),
        "gbps": round(total / 1e9 / wall, 3),
        "stage_busy_s": round(stats.get("stage_busy_s", 0.0), 4),
        "stage_d2h_s": round(stats.get("stage_d2h_s", 0.0), 4),
        "stage_serialize_s": round(stats.get("stage_serialize_s", 0.0), 4),
        "stage_hash_s": round(stats.get("stage_hash_s", 0.0), 4),
    }


def main() -> None:
    total_mb = int(os.environ.get("STAGING_BENCH_MB", "512"))
    arrays = int(os.environ.get("STAGING_BENCH_ARRAYS", "8"))
    arrs = build_host_state(total_mb, arrays)
    total_gb = sum(a.nbytes for a in arrs) / 1e9
    log(f"staging micro-bench: {total_gb:.2f} GB across {arrays} host arrays")

    # Warmup: absorb one-time costs (thread-pool spawn, hashing-engine
    # operator caches, lazy imports) on a tiny slice so the matrix's FIRST
    # cell isn't charged ~0.2s the others never pay.
    run_config([a[:64] for a in arrs[:1]], stream=True, checksums=True,
               dedup=True)

    # The ablation matrix: diffing rows bisects which staging feature a
    # regression lives in. "full" is the production default path (chunked
    # v2 tree hashing); "serial_hash" pins the v1 serial fold (grain 0) so
    # chunked-vs-serial hashing stays directly comparable every run.
    matrix = {
        "full": dict(stream=True, checksums=True, dedup=True),
        "serial_hash": dict(
            stream=True, checksums=True, dedup=True, hash_grain=0
        ),
        "no_dedup_sha": dict(stream=True, checksums=True, dedup=False),
        "no_digests": dict(stream=True, checksums=False, dedup=False),
        "no_stream": dict(stream=False, checksums=True, dedup=True),
    }
    results = {}
    for name, cfg in matrix.items():
        results[name] = run_config(arrs, **cfg)
        log(f"  {name}: {results[name]}")

    full, bare = results["full"], results["no_digests"]

    def hash_cost(cell: dict) -> float:
        # Wall paid over the digest-free baseline: the cell's hashing bill.
        return round(max(0.0, cell["wall_s"] - bare["wall_s"]), 4)

    # Optional hash-grain x hash-worker sweep (serial v1 vs chunked v2 at
    # several grains, across pool widths): the tuning map for
    # TORCHSNAPSHOT_TPU_HASH_CHUNK_BYTES / _HASH_WORKERS. The full sweep is
    # slow-lane material (pre_commit.yaml); the fast smoke skips it.
    hash_sweep = None
    if os.environ.get("STAGING_BENCH_HASH_SWEEP"):
        default_grain = knobs.get_hash_chunk_bytes()
        default_workers = knobs.get_hash_workers()
        grains = {
            "serial": 0,
            f"g{default_grain // (1024 * 1024)}m": default_grain,
            f"g{max(1, default_grain // 4) // (1024 * 1024)}m": max(
                1024 * 1024, default_grain // 4
            ),
        }
        workers = sorted({1, default_workers, 2 * default_workers})
        hash_sweep = {}
        for gname, grain in grains.items():
            for w in workers:
                cell = run_config(
                    arrs,
                    stream=True,
                    checksums=True,
                    dedup=True,
                    hash_grain=grain,
                    hash_workers=w,
                )
                cell["hash_cost_s"] = hash_cost(cell)
                hash_sweep[f"{gname}_w{w}"] = cell
                log(f"  hash sweep {gname}_w{w}: {cell}")

    print(
        json.dumps(
            {
                "metric": "staging_overhead_gbps",
                "value": results["full"]["gbps"],
                "unit": "GB/s",
                "detail": {
                    "size_gb": round(total_gb, 3),
                    "arrays": arrays,
                    "configs": results,
                    # The hash satellite's measurable delta: staging rate
                    # with vs without the digest pipeline — chunked (the
                    # default) and the serial v1 fold side by side.
                    "hash_cost_s": hash_cost(full),
                    "serial_hash_cost_s": hash_cost(results["serial_hash"]),
                    "hash_sweep": hash_sweep,
                    "env": {"knobs": knobs.env_fingerprint()},
                },
            }
        )
    )


if __name__ == "__main__":
    main()
