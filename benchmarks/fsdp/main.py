"""Sharded (FSDP-style) transformer save/load benchmark
(reference ``benchmarks/fsdp/main.py:35-72``: 1.9 B-param transformer,
flat params as ShardedTensor).

TPU equivalent: the flagship transformer's params FSDP+TP-sharded over a
(dp, tp) mesh; measures sync take, async stall, and restore.

  python benchmarks/fsdp/main.py --layers 8 --d-model 2048
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from benchmarks.common import maybe_init_distributed  # noqa: E402


def main() -> None:
    maybe_init_distributed()
    parser = argparse.ArgumentParser()
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--tp", type=int, default=0, help="0 = auto")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        shard_params,
    )
    from torchsnapshot_tpu.tricks.train_state import Box, PyTreeStateful

    n = len(jax.devices())
    tp = args.tp or (2 if n % 2 == 0 else 1)
    mesh = Mesh(np.array(jax.devices()).reshape(n // tp, tp), ("dp", "tp"))
    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=args.d_model,
        n_heads=max(4, args.d_model // 128),
        n_layers=args.layers,
        d_ff=4 * args.d_model,
    )
    _, params = init_params(cfg)
    params = shard_params(params, mesh, fsdp=True)
    jax.block_until_ready(params)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(params))
    gb = nbytes / 1e9
    print(f"{gb:.2f} GB params on mesh {dict(mesh.shape)}")

    holder = Box(params)
    app_state = {"params": PyTreeStateful(holder)}
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "ckpt")
        t0 = time.perf_counter()
        Snapshot.take(path, app_state)
        sync_s = time.perf_counter() - t0
        print(f"sync take: {sync_s:.2f}s ({gb / sync_s:.2f} GB/s)")

        t0 = time.perf_counter()
        pending = Snapshot.async_take(os.path.join(tmp, "ckpt2"), app_state)
        stall_s = time.perf_counter() - t0
        pending.wait()
        print(f"async stall: {stall_s:.2f}s")

        restored = Box(jax.tree.map(jnp.zeros_like, params))
        t0 = time.perf_counter()
        Snapshot(path).restore({"params": PyTreeStateful(restored)})
        load_s = time.perf_counter() - t0
        print(f"restore: {load_s:.2f}s ({gb / load_s:.2f} GB/s)")
        ok = all(
            np.array_equal(
                np.ascontiguousarray(np.asarray(a)).view(np.uint8),
                np.ascontiguousarray(np.asarray(b)).view(np.uint8),
            )
            for a, b in zip(
                jax.tree_util.tree_leaves(params),
                jax.tree_util.tree_leaves(restored.value),
            )
        )
        print(f"bit-exact: {ok}")


if __name__ == "__main__":
    main()
