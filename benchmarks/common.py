"""Shared helpers for the benchmark harnesses."""

import os


def maybe_init_distributed() -> None:
    """Join a multi-host run when ``BENCH_DISTRIBUTED=1`` (exported by
    ``benchmarks/run_tpu_vm.sh`` on every pod worker).

    On Cloud TPU, ``jax.distributed.initialize()`` auto-configures the
    coordinator address, process id, and process count from the TPU metadata
    service — no flags needed. Once initialized, the library's coordinator
    rides the jax coordination service, every host writes its partition of
    each checkpoint, and the benchmark's printed per-host numbers aggregate
    across ``jax.process_count()`` hosts. Must run before any other jax
    call. A no-op in local runs.
    """
    if os.environ.get("BENCH_DISTRIBUTED") in ("1", "true"):
        import jax

        jax.distributed.initialize()
