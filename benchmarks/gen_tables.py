#!/usr/bin/env python
"""Regenerate published headline numbers from the newest BENCH_r*.json.

The driver runs ``bench.py`` on real TPU hardware at the end of every round
and records the parsed result in ``BENCH_r<N>.json``. Hand-maintained copies
of those numbers drift (round 3 shipped a README quoting round 2's stall);
this script makes the published tables a *projection of the artifact*:

    python benchmarks/gen_tables.py            # rewrite the generated blocks
    python benchmarks/gen_tables.py --check    # exit 1 if out of sync (CI)

Generated regions are delimited by ``<!-- BEGIN/END GENERATED: <tag> -->``
markers in ``benchmarks/README.md`` and the root ``README.md``; everything
outside the markers is hand-written commentary and never touched.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def newest_bench() -> tuple[str, dict]:
    best_round, best_path = -1, None
    for path in glob.glob(os.path.join(ROOT, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m and int(m.group(1)) > best_round:
            best_round, best_path = int(m.group(1)), path
    if best_path is None:
        raise SystemExit("no BENCH_r*.json artifact found at the repo root")
    with open(best_path) as f:
        return os.path.basename(best_path), json.load(f)


def render_headline_table(src: str, bench: dict) -> str:
    parsed = bench["parsed"]
    d = parsed["detail"]
    ab = (
        f"{d['sync_take_gbps']:.3f} vs {d['naive_save_gbps']:.3f} GB/s "
        f"({d['speedup_vs_naive_sync']:.2f}x, {d['ab_reps']} interleaved reps; "
        f"sync {min(d['sync_gbps_all']):.4f}-{max(d['sync_gbps_all']):.4f}, "
        f"naive {min(d['naive_gbps_all']):.4f}-{max(d['naive_gbps_all']):.4f})"
    )
    lines = [
        f"Headline (`bench.py`, regenerated from `{src}` — the driver's run "
        "on the real chip; do not edit by hand, run "
        "`python benchmarks/gen_tables.py`):",
        "",
        "| Metric | Value |",
        "|---|---|",
        f"| Checkpoint | {d['size_gb']:.2f} GB bf16 params in HBM |",
        f"| async-take train-step stall, steady-state | **{d['async_stall_s']:.3f} s** |",
        f"| async-take stall, first take (incl. XLA compile) | {d['async_stall_cold_s']:.3f} s |",
        f"| Background drain (D2H + storage I/O) | {d['background_drain_s']:.2f} s |",
    ]
    degenerate = bool((d.get("link_probe") or {}).get("degenerate"))
    if d.get("drain_vs_link") is not None and not degenerate:
        lines += [
            f"| Drain rate vs link rate bracketing it | {d['drain_gbps']:.4f} / "
            f"{d['link_gbps_around_drain']:.4f} GB/s = **{d['drain_vs_link']:.2f}x** "
            "(>= 0.85 means the staging stream saturates the transfer) |",
        ]
    elif degenerate:
        lines += [
            f"| Drain rate | {d['drain_gbps']:.4f} GB/s (link probe degenerate "
            "on this host — a host-memory memcpy, not a device link; "
            "vs-link ratio not comparable) |",
        ]
    if not degenerate:
        lines += [
            f"| Reference-equivalent stall on this link | >= {d['ref_equiv_stall_s']:.1f} s "
            f"(**~{round(parsed['vs_baseline'])}x**) |",
        ]
    lines += [
        f"| Sync take vs naive blocking save | {ab} |",
        f"| Restore | {'bit-exact' if d['restore_bit_exact'] else 'MISMATCH'} |",
    ]
    return "\n".join(lines)


def newest_multichip() -> tuple[str, dict] | None:
    """The newest MULTICHIP_r*.json that carries a drain-scaling curve
    (rounds 1–5 were pass/fail smokes with no curve — skipped)."""
    best = None
    for path in glob.glob(os.path.join(ROOT, "MULTICHIP_r*.json")):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        if not (rec.get("drain_scaling") or {}).get("detail", {}).get("curve"):
            continue
        if best is None or int(m.group(1)) > best[0]:
            best = (int(m.group(1)), os.path.basename(path), rec)
    if best is None:
        return None
    return best[1], best[2]


def render_multichip_table(src: str, rec: dict) -> str:
    curve = rec["drain_scaling"]["detail"]["curve"]
    lines = [
        f"Per-device drain scaling + elastic reshard (`benchmarks/multichip/`"
        f" + `benchmarks/reshard/`, regenerated from `{src}`; do not edit by "
        "hand, run `python benchmarks/gen_tables.py`):",
        "",
        "| Devices | Drain GB/s | stage_busy s | io_busy s |",
        "|---|---|---|---|",
    ]
    for c in curve:
        lines.append(
            f"| {c['devices']} | {c['drain_gbps']:.3f} | "
            f"{c['stage_busy_s']:.2f} | {c['io_busy_s']:.2f} |"
        )
    reshard = rec.get("reshard") or {}
    det = reshard.get("detail") or {}
    if det.get("cells"):
        lines += [
            "",
            "| Reshard cell | GB/s | origin / theoretical-overlap bytes |",
            "|---|---|---|",
        ]
        for c in det["cells"]:
            lines.append(
                f"| {c['cell']} | {c['reshard_gbps']:.3f} | "
                f"**{c['origin_ratio']:.2f}×** (bit-exact) |"
            )
    for f in det.get("fleet") or []:
        lines.append(
            f"| fleet K={f['k']} (replicated overlap) | — | "
            f"**{f['origin_ratio_vs_one_payload']:.2f}×** one payload, "
            f"every chunk origin-fetched once fleet-wide |"
        )
    if rec.get("host_note"):
        lines += ["", f"*{rec['host_note']}*"]
    return "\n".join(lines)


def render_job_timeline(src: str, bench: dict) -> str | None:
    """Flight-recorder overhead A/B + job step-telemetry timeline from the
    round artifact; ``None`` when the artifact predates the leg (or it
    failed fail-soft) so older rounds keep a valid README."""
    d = bench["parsed"]["detail"]
    ab = d.get("recorder_ab")
    jt = d.get("job_timeline")
    if not ab and not jt:
        return None
    lines = [
        f"Flight recorder + per-step job telemetry (`bench.py`, regenerated "
        f"from `{src}`; do not edit by hand, run "
        "`python benchmarks/gen_tables.py`):",
        "",
    ]
    if ab:
        verdict = (
            "within the 1% always-on budget"
            if ab.get("within_budget")
            else "OVER the 1% always-on budget on this host"
        )
        lines += [
            "| Recorder A/B (async drain wall, medians) | Value |",
            "|---|---|",
            f"| recorder on | {ab['on_drain_wall_s']:.4f} s |",
            f"| recorder off | {ab['off_drain_wall_s']:.4f} s |",
            f"| overhead | **{ab['overhead_frac'] * 100:+.2f}%** ({verdict}, "
            f"{ab['reps']} interleaved reps) |",
        ]
    if jt:
        summary = jt.get("summary") or {}
        stall = summary.get("stall_s") or {}
        kinds = sorted({a.get("kind", "?") for a in jt.get("anomalies") or []})
        lines += [
            "",
            f"Job-mode timeline (`take(job=, step=)` × {jt.get('steps')}): "
            f"{jt.get('steps_recorded')} step records, train-loop stall "
            f"p50 {stall.get('p50', 0.0):.3f} s / max {stall.get('max', 0.0):.3f} s, "
            + (
                f"health detectors flagged {kinds}"
                if kinds
                else "health detectors quiet (zero false positives)"
            )
            + ".",
            "",
            "```",
            *(jt.get("timeline") or []),
            "```",
        ]
    return "\n".join(lines)


def _host_description(d: dict) -> str:
    """Where the round actually ran, from the artifact's link-probe record
    (older artifacts predate the record and were all driver runs on a real
    v5e). The README must never claim TPU hardware for a CPU-host round."""
    probe = d.get("link_probe") or {}
    platform = probe.get("platform")
    if platform is None or platform == "tpu":
        return "driver run on a real TPU v5e chip, tunneled D2H link"
    cpus = (probe.get("host") or {}).get("cpus")
    return (
        f"{platform} backend on a {cpus}-vCPU host"
        if cpus
        else f"{platform} backend"
    )


def render_readme_bullet(src: str, bench: dict) -> str:
    parsed = bench["parsed"]
    d = parsed["detail"]
    line = (
        f"- **Measured headline** ({_host_description(d)}; `{src}`): "
        f"async-take train-step stall "
        f"**{d['async_stall_s']:.3f} s steady-state** "
        f"({d['async_stall_cold_s']:.3f} s first take incl. XLA compile) for "
        f"a {d['size_gb']:.2f} GB bf16 state"
    )
    # The capture-to-host comparison only means something against a real
    # device link; a degenerate probe (host-memory memcpy) would render
    # as a nonsense "~0x better (>= 0.0 s)".
    if not (d.get("link_probe") or {}).get("degenerate"):
        line += (
            f" — ~{round(parsed['vs_baseline'])}x better than a "
            f"capture-to-host design on the same link "
            f"(>= {d['ref_equiv_stall_s']:.1f} s)"
        )
    return line + "; restore bit-exact."


def splice(text: str, tag: str, payload: str) -> str:
    begin = f"<!-- BEGIN GENERATED: {tag} -->"
    end = f"<!-- END GENERATED: {tag} -->"
    pattern = re.compile(
        re.escape(begin) + r".*?" + re.escape(end), flags=re.DOTALL
    )
    if not pattern.search(text):
        raise SystemExit(f"marker pair for {tag!r} not found")
    return pattern.sub(begin + "\n" + payload + "\n" + end, text)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit 1 if the generated blocks are out of sync with the artifact",
    )
    args = parser.parse_args()

    src, bench = newest_bench()
    targets = [
        (
            os.path.join(ROOT, "benchmarks", "README.md"),
            "bench-headline",
            render_headline_table(src, bench),
        ),
        (
            os.path.join(ROOT, "README.md"),
            "bench-headline-bullet",
            render_readme_bullet(src, bench),
        ),
    ]
    jt = render_job_timeline(src, bench)
    if jt is not None:
        targets.append(
            (
                os.path.join(ROOT, "benchmarks", "README.md"),
                "job-timeline",
                jt,
            )
        )
    mc = newest_multichip()
    if mc is not None:
        targets.append(
            (
                os.path.join(ROOT, "benchmarks", "README.md"),
                "multichip-scaling",
                render_multichip_table(mc[0], mc[1]),
            )
        )
    stale = []
    for path, tag, payload in targets:
        with open(path) as f:
            text = f.read()
        updated = splice(text, tag, payload)
        if updated != text:
            if args.check:
                stale.append(path)
            else:
                with open(path, "w") as f:
                    f.write(updated)
                print(f"regenerated {tag} in {os.path.relpath(path, ROOT)}")
        else:
            print(f"{os.path.relpath(path, ROOT)}: {tag} up to date")
    if stale:
        print(
            "STALE generated tables (run `python benchmarks/gen_tables.py`): "
            + ", ".join(os.path.relpath(p, ROOT) for p in stale)
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
